"""Batched CasperIMD: beacon-chain stage-1 fork choice on the batched
engine — slot-aligned block producers, attester committees, GHOST-like
attestation counting.

Reference semantics: protocols/CasperIMD.java (Attestation :105-149, fork
choice best/countAttestations :204-288, onBlock/onAttestation lazy
reevaluation :298-353, buildBlock :383-428, init schedule :472-508,
default ByzBlockProducerWF(0) producer :647-707) via the oracle port
`protocols/casper.py`.

TPU-first design — everything is a HEIGHT:

  * heights are unique per block by construction (producer i owns heights
    ≡ i+1 mod bpc; same-height forks are "slashable, unsupported",
    CasperIMD.java:214), so the block table is indexed BY height:
    exists/parent/time columns `[mH]`, genesis at 0;
  * ancestry is a dense `anc[mH, mH]` bool matrix updated incrementally
    at block creation (`anc[h] = anc[parent] | onehot(parent)`) — the
    reference's pointer walks (firstCommonAncestor, hasDirectLink,
    Attestation.hs construction) all become row ops:
      - first common ancestor of (a, b) = argmax height of anc[a] & anc[b]
      - attests(att, H)  =  anc[att_head, H] & (H >= att_head - cl)
        (hs = strict ancestors of the head within cycleLength, :113-119)
  * countAttestations(start, H) = one [N, mH] x [mH, mA] mat-product:
    branch row (anc[start] | start, heights > H) against the block
    inclusion matrix `blk_att[mH, mA]` windowed by att_height < cur,
    OR'd with directly-received attestations whose head lies on the
    branch — the count lands on the MXU instead of a pointer chase;
  * the periodic production/vote schedule (init :472-508) runs as size-0
    self-messages with explicit arrivals that re-arm themselves, so the
    engine's empty-ms jump skips the 8-second slots (TICK_INTERVAL None);
  * one attester committee votes per slot and its members share one
    arrival tick, so the attestation broadcast emission is [apr x N]
    rows, not [attesters x N];
  * the default init's producer 0 is ByzBlockProducerWF(delay=0)
    (:647-707): it waits for the parent block and replies at
    perfect_date = SLOT * toSend via a TWFB self-message.

Approximations (documented): tie-breaks compare (proposal_time, height)
instead of creation ids; `random_on_ties` uses the counter hash; the
oracle's same-ms LIFO interleavings of task vs arrival are simultaneous.

Byzantine producer variants (make_casper byz_variant/byz_delay): besides
the default "wf" (ByzBlockProducerWF :647-707), the head-start producer
"delay" (ByzBlockProducer :511-580 — fires delay ms into its slot and
builds on the best ancestor below toSend), "sf" (ByzBlockProducerSF
:583-604 — skips its direct father to steal its transactions), and "ns"
(ByzBlockProducerNS :610-640 — skips its father when the father skipped
the grandfather).  All run on the batched path, so Byzantine sweeps for
the blockchain family are replica-parallel like Handel's.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..core.node import build_node_columns
from ..core.registries import registry_network_latencies
from ..engine import BatchedNetwork, BatchedProtocol, Emission
from ..engine.rng import hash32
from .casper import SLOT_DURATION, Attester, BlockProducer, CasperIMD, CasperParameters


class BatchedCasper(BatchedProtocol):
    MSG_TYPES = ["BLOCK", "ATT", "TBP", "TATT", "TWF", "TWFB", "TBYZ"]
    PAYLOAD_WIDTH = 2
    TICK_INTERVAL = None  # all timing is explicit-arrival self-messages

    def __init__(
        self,
        params: CasperParameters,
        roles: dict,
        max_heights: int,
        byz_variant: str = "wf",
        byz_delay: int = 0,
    ):
        if byz_variant not in ("wf", "delay", "sf", "ns"):
            raise ValueError(f"unknown byz_variant {byz_variant!r}")
        self.byz_variant = byz_variant
        self.byz_delay = byz_delay
        self.params = params
        self.mh = max_heights
        self.apr = params.attesters_per_round
        self.cl = params.cycle_length
        self.bpc = params.block_producers_count
        self.ma = max_heights * self.apr  # attestation slots: (h-1)*apr + j
        self.n_nodes = int(roles["n_nodes"])
        self.is_att = jnp.asarray(roles["is_att"])
        self.is_bp = jnp.asarray(roles["is_bp"])  # honest producers (not bp0)
        self.bp0 = int(roles["bp0"])  # the default WF producer's node id
        self.att_ids = jnp.asarray(roles["att_ids"], jnp.int32)
        self.att_cidx = jnp.asarray(roles["att_cidx"], jnp.int32)  # i // cl
        self.committee = jnp.asarray(roles["committee"], jnp.int32)  # [cl, apr]
        self.prod_ids = jnp.asarray(roles["prod_ids"], jnp.int32)  # bp0 + honest
        self.all_ids = jnp.arange(self.n_nodes, dtype=jnp.int32)
        # static window matrix: attestation a may sit in block cur's count
        # window only when att_h(a) < cur (heights [H+1, cur-1], :271-276)
        att_h = np.arange(self.ma) // self.apr + 1
        self.att_h = jnp.asarray(att_h, jnp.int32)
        self.win = jnp.asarray(
            att_h[None, :] < np.arange(max_heights)[:, None]
        )  # [mH, mA]

    def msg_size(self, mtype: int) -> int:
        return 1 if self.MSG_TYPES[mtype] in ("BLOCK", "ATT") else 0

    def proto_init(self, n_nodes: int):
        mh, ma, n = self.mh, self.ma, n_nodes
        seen = jnp.zeros((n, mh), bool).at[:, 0].set(True)  # genesis known
        return {
            # global block table (one block per height; 0 = genesis)
            "blk_exists": jnp.zeros(mh, bool).at[0].set(True),
            "blk_parent": jnp.full(mh, -1, jnp.int32),
            "blk_time": jnp.zeros(mh, jnp.int32),
            "anc": jnp.zeros((mh, mh), bool),
            "blk_att": jnp.zeros((mh, ma), bool),
            # global attestation table
            "att_exists": jnp.zeros(ma, bool),
            "att_head": jnp.zeros(ma, jnp.int32),
            # per-node state
            "head": jnp.zeros(n, jnp.int32),
            "seen": seen,
            "rec_att": jnp.zeros((n, ma), bool),
            "reeval": jnp.zeros((n, mh), bool),
            # ByzBlockProducer* bookkeeping (row bp0 only; :511-707):
            # wf_to_send doubles as every variant's toSend cursor
            "wf_to_send": jnp.full(n, 1, jnp.int32),
            "wf_late": jnp.zeros(n, jnp.int32),
            "wf_on_time": jnp.zeros(n, jnp.int32),
            "byz_direct": jnp.zeros(n, jnp.int32),  # onDirectFather
            "byz_older": jnp.zeros(n, jnp.int32),  # onOlderAncestor
            "byz_skipped": jnp.zeros(n, jnp.int32),  # NS skipped
        }

    # -- fork choice ---------------------------------------------------------
    def _count(self, proto, rec_att, start, hcn):
        """countAttestations(start, H) vectorized over nodes
        (CasperIMD.java:262-288).  start/hcn are [N] heights."""
        mh = self.mh
        hrange = jnp.arange(mh, dtype=jnp.int32)
        branch = (
            proto["anc"][start] | jax.nn.one_hot(start, mh, dtype=bool)
        ) & (hrange[None, :] > hcn[:, None])
        # from blocks: exists cur on the branch including a within window
        inc = (proto["blk_att"] & self.win).astype(jnp.int32)
        from_blocks = (branch.astype(jnp.int32) @ inc) > 0  # [N, mA]
        from_blocks = from_blocks & (self.att_h[None, :] > hcn[:, None])
        # from direct reception: attestation's head lies on the branch
        from_recv = rec_att & branch[:, proto["att_head"]]
        # attests(H): H strict ancestor of the head, within cycleLength
        att_ok = (
            proto["att_exists"][None, :]
            & proto["anc"][proto["att_head"]][:, hcn].T  # [N, mA]
            & (hcn[:, None] >= proto["att_head"][None, :] - self.cl)
        )
        return jnp.sum(att_ok & (from_blocks | from_recv), axis=1).astype(jnp.int32)

    def _best(self, state, proto, rec_att, o1, o2, mask):
        """Vectorized pairwise best(o1, o2) (CasperIMD.java:204-257)."""
        p = self.params
        anc = proto["anc"]
        same = o1 == o2
        direct = anc[o1, o2] | anc[o2, o1]
        hi = jnp.maximum(o1, o2)
        # first common (strict) ancestor
        common = anc[o1] & anc[o2]
        hr = jnp.arange(self.mh, dtype=jnp.int32)
        hcn = jnp.max(jnp.where(common, hr[None, :], 0), axis=1).astype(jnp.int32)
        v1 = self._count(proto, rec_att, o1, hcn)
        v2 = self._count(proto, rec_att, o2, hcn)
        if p.random_on_ties:
            coin = (
                hash32(state.seed, state.time, self.all_ids, o1, o2) & 1
            ) == 0
            tie = jnp.where(coin, o1, o2)
        else:
            k1 = proto["blk_time"][o1] * self.mh + o1
            k2 = proto["blk_time"][o2] * self.mh + o2
            tie = jnp.where(k1 >= k2, o1, o2)
        by_votes = jnp.where(v1 > v2, o1, jnp.where(v2 > v1, o2, tie))
        win = jnp.where(same, o1, jnp.where(direct, hi, by_votes))
        return jnp.where(mask, win, o1)

    def _reevaluate(self, state, proto, nodes_mask):
        """Lazy head re-election: fold best over the pending candidates
        (reevaluateHead, CasperIMD.java:348-353)."""
        rec_att = proto["rec_att"]

        def body(i, carry):
            head, reeval = carry
            cand = reeval[:, i] & nodes_mask
            head = self._best(
                state, proto, rec_att, head, jnp.full_like(head, i), cand
            )
            return head, reeval

        head, _ = lax.fori_loop(1, self.mh, body, (proto["head"], proto["reeval"]))
        reeval = jnp.where(nodes_mask[:, None], False, proto["reeval"])
        return dict(proto, head=head, reeval=reeval)

    # -- block building (buildBlock, :383-428) -------------------------------
    def _build_blocks(self, state, proto, mask, base, height):
        """Producers in `mask` create block `height[n]` on parent `base[n]`:
        include every received attestation on the parent chain (within the
        cycle window) not already included in it."""
        mh = self.mh
        t = state.time
        hrange = jnp.arange(mh, dtype=jnp.int32)
        # parent-chain blocks within the window [height - cl, ...]
        chain = (
            proto["anc"][base] | jax.nn.one_hot(base, mh, dtype=bool)
        ) & (hrange[None, :] >= (height - self.cl)[:, None]) & (hrange[None, :] > 0)
        chain32 = chain.astype(jnp.int32)
        included = (chain32 @ proto["blk_att"].astype(jnp.int32)) > 0  # [N, mA]
        head_on_chain = chain[:, proto["att_head"]]  # [N, mA]
        mine = (
            proto["rec_att"]
            & head_on_chain
            & (self.att_h[None, :] < height[:, None])
            & ~included
        )
        # genesis-headed attestations: head 0 is never on `chain` (height>0
        # filter) but the oracle's walk does visit down to the window edge;
        # head==0 attestations only exist for votes made on genesis
        mine0 = (
            proto["rec_att"]
            & (proto["att_head"][None, :] == 0)
            & (0 >= height - self.cl)[:, None]
            & (self.att_h[None, :] < height[:, None])
            & ~included
        )
        mine = mine | mine0

        # scatter the new blocks into the global tables (heights unique)
        w_h = jnp.where(mask, height, mh)  # OOB -> dropped
        proto = dict(proto)
        proto["blk_exists"] = proto["blk_exists"].at[w_h].set(True, mode="drop")
        proto["blk_parent"] = proto["blk_parent"].at[w_h].set(base, mode="drop")
        proto["blk_time"] = proto["blk_time"].at[w_h].set(t, mode="drop")
        anc_new = proto["anc"][base] | jax.nn.one_hot(base, mh, dtype=bool)
        proto["anc"] = proto["anc"].at[w_h].set(anc_new, mode="drop")
        proto["blk_att"] = proto["blk_att"].at[w_h].set(mine, mode="drop")
        # the producer's head becomes its new block immediately (:425-427)
        proto["head"] = jnp.where(mask, height, proto["head"])
        proto["seen"] = proto["seen"].at[self.all_ids, w_h].set(True, mode="drop")

        # broadcast rows restricted to the (few, static) producer ids
        kp = self.prod_ids.shape[0] * self.n_nodes
        em = Emission(
            mask=jnp.repeat(mask[self.prod_ids], self.n_nodes),
            from_idx=jnp.repeat(self.prod_ids, self.n_nodes),
            to_idx=jnp.tile(self.all_ids, self.prod_ids.shape[0]),
            mtype=self.mtype("BLOCK"),
            payload=jnp.stack(
                [
                    jnp.repeat(height[self.prod_ids], self.n_nodes),
                    jnp.zeros(kp, jnp.int32),
                ],
                axis=1,
            ),
            send_time=jnp.broadcast_to(
                t + self.params.block_construction_time, (kp,)
            ).astype(jnp.int32),
        )
        return proto, em

    def initial_emissions(self, net, state):
        """The init task schedule (CasperIMD.java:472-508) as explicit
        arrivals: bp0 (WF) at SLOT, honest producer i at SLOT*(i+1),
        attester committee c at SLOT*(1+c)+4000."""
        n = self.n_nodes
        ids = self.all_ids
        arr_bp = jnp.where(
            self.is_bp, SLOT_DURATION * (ids - self.bp0 + 1), 1
        ).astype(jnp.int32)
        if self.byz_variant == "wf":
            em0 = Emission(  # WF producer kick-off tick
                mask=ids == self.bp0,
                from_idx=ids,
                to_idx=ids,
                mtype=self.mtype("TWF"),
                payload=jnp.zeros((n, 2), jnp.int32),
                arrival=jnp.full(n, SLOT_DURATION, jnp.int32),
            )
        else:
            # delay/sf/ns: periodic at SLOT + delay, period SLOT*bpc
            # (init registration, CasperIMD.java:486-492)
            em0 = Emission(
                mask=ids == self.bp0,
                from_idx=ids,
                to_idx=ids,
                mtype=self.mtype("TBYZ"),
                payload=jnp.zeros((n, 2), jnp.int32),
                arrival=jnp.full(n, SLOT_DURATION + self.byz_delay, jnp.int32),
            )
        ems = [
            em0,
            Emission(
                mask=self.is_bp,
                from_idx=ids,
                to_idx=ids,
                mtype=self.mtype("TBP"),
                payload=jnp.zeros((n, 2), jnp.int32),
                arrival=arr_bp,
            ),
        ]
        cidx = jnp.zeros(n, jnp.int32)
        cidx = cidx.at[self.att_ids].set(
            jnp.asarray(
                np.arange(len(np.asarray(self.att_ids))) % self.cl, jnp.int32
            )
        )
        arr_att = (SLOT_DURATION * (1 + cidx) + 4000).astype(jnp.int32)
        ems.append(
            Emission(
                mask=self.is_att,
                from_idx=ids,
                to_idx=ids,
                mtype=self.mtype("TATT"),
                payload=jnp.zeros((n, 2), jnp.int32),
                arrival=arr_att,
            )
        )
        return ems

    # -- per-event processing ------------------------------------------------
    def deliver(self, net, state, deliver_mask):
        p = self.params
        proto = dict(state.proto)
        n, mh, ma = self.n_nodes, self.mh, self.ma
        t = state.time
        ids = self.all_ids
        to = state.msg_to
        pay0 = state.msg_payload[:, 0]
        pay1 = state.msg_payload[:, 1]
        m_ = lambda s: deliver_mask & (state.msg_type == self.mtype(s))
        is_blk, is_att = m_("BLOCK"), m_("ATT")
        is_tbp, is_tatt, is_twf, is_twfb = m_("TBP"), m_("TATT"), m_("TWF"), m_("TWFB")
        emissions = []
        slot_now = (t // SLOT_DURATION).astype(jnp.int32)

        # ---- 1. attestation arrivals (onAttestation, :316-337) ------------
        h0 = jnp.clip(pay0, 0, ma - 1)
        new_att = jnp.zeros((n, ma), bool).at[to, h0].max(is_att, mode="drop")
        new_att = new_att & proto["att_exists"][None, :]
        proto["rec_att"] = proto["rec_att"] | new_att
        # reevaluate the attested head when the block is known; the
        # [N, mA] x [mA, mH] product beats a 2D scatter on TPU
        head_oh = jax.nn.one_hot(proto["att_head"], mh, dtype=jnp.int32)
        att_heads_hit = (new_att.astype(jnp.int32) @ head_oh) > 0
        proto["reeval"] = proto["reeval"] | (att_heads_hit & proto["seen"])

        # ---- 2. block arrivals (onBlock, :298-314; slot gate is dead
        # code in the reference — delta sign bug kept verbatim) -------------
        bh = jnp.clip(pay0, 0, mh - 1)
        new_blk = jnp.zeros((n, mh), bool).at[to, bh].max(is_blk, mode="drop")
        new_blk = new_blk & ~proto["seen"] & proto["blk_exists"][None, :]
        got_blk = jnp.any(new_blk, axis=1)
        proto["seen"] = proto["seen"] | new_blk
        # reevaluate old head later; immediate pairwise best against the
        # highest new block (BlockChainNode.onBlock head update)
        hr = jnp.arange(mh, dtype=jnp.int32)
        best_new = jnp.max(jnp.where(new_blk, hr[None, :], 0), axis=1).astype(jnp.int32)
        proto["reeval"] = proto["reeval"] | (
            jax.nn.one_hot(proto["head"], mh, dtype=bool) & got_blk[:, None]
        )
        proto["reeval"] = proto["reeval"] | new_blk
        proto["head"] = self._best(
            state, proto, proto["rec_att"], proto["head"], best_new, got_blk
        )

        if self.byz_variant == "wf":
            # WF producer response (:660-676): fires when the awaited parent
            # (toSend-1) is among THIS tick's new blocks — membership, not
            # the max, so a same-tick higher block cannot mask it
            want = jnp.clip(proto["wf_to_send"] - 1, 0, mh - 1)
            wf_hit = (ids == self.bp0) & new_blk[ids, want]
            th = proto["wf_to_send"]
            perfect = SLOT_DURATION * th + self.byz_delay
            fire_now = wf_hit & (t >= perfect)
            fire_later = wf_hit & ~fire_now
            proto["wf_late"] = proto["wf_late"] + fire_now.astype(jnp.int32)
            proto["wf_on_time"] = proto["wf_on_time"] + fire_later.astype(jnp.int32)
            proto["wf_to_send"] = jnp.where(wf_hit, th + self.bpc, proto["wf_to_send"])
            emissions.append(
                Emission(  # the scheduled build (registerTask(r, perfectDate))
                    mask=wf_hit,
                    from_idx=ids,
                    to_idx=ids,
                    mtype=self.mtype("TWFB"),
                    payload=jnp.stack([want, th], axis=1),
                    arrival=jnp.maximum(perfect, t + 1).astype(jnp.int32),
                )
            )

            # ---- 3. WF kick-off (periodic while nothing produced, :692-698)
            twf = jnp.zeros(n, bool).at[to].max(is_twf, mode="drop")
            wf_kick = twf & (proto["head"] == 0) & (proto["wf_to_send"] == 1)
            proto["wf_to_send"] = jnp.where(wf_kick, 1 + self.bpc, proto["wf_to_send"])
            emissions.append(
                Emission(  # re-arm the kick-off watchdog
                    mask=twf,
                    from_idx=ids,
                    to_idx=ids,
                    mtype=self.mtype("TWF"),
                    payload=jnp.zeros((n, 2), jnp.int32),
                    arrival=jnp.broadcast_to(
                        t + SLOT_DURATION * self.bpc, (n,)
                    ).astype(jnp.int32),
                )
            )
        else:
            twf = jnp.zeros(n, bool)
            wf_kick = jnp.zeros(n, bool)

        # ---- 4. honest producers fire (reevaluate + build, :365-381) ------
        tbp = jnp.zeros(n, bool).at[to].max(is_tbp, mode="drop")
        emissions.append(
            Emission(
                mask=tbp,
                from_idx=ids,
                to_idx=ids,
                mtype=self.mtype("TBP"),
                payload=jnp.zeros((n, 2), jnp.int32),
                arrival=jnp.broadcast_to(
                    t + SLOT_DURATION * self.bpc, (n,)
                ).astype(jnp.int32),
            )
        )

        # ---- 5. attesters fire (vote at 4 s, :444-464) --------------------
        tatt = jnp.zeros(n, bool).at[to].max(is_tatt, mode="drop")
        emissions.append(
            Emission(
                mask=tatt,
                from_idx=ids,
                to_idx=ids,
                mtype=self.mtype("TATT"),
                payload=jnp.zeros((n, 2), jnp.int32),
                arrival=jnp.broadcast_to(
                    t + SLOT_DURATION * self.cl, (n,)
                ).astype(jnp.int32),
            )
        )

        # byz head-start producers (delay/sf/ns) fire on their own beat
        is_tbyz = m_("TBYZ")
        tbyz = jnp.zeros(n, bool).at[to].max(is_tbyz, mode="drop")

        # one reevaluation pass for every node acting this tick
        acting = tbp | tatt | twf | tbyz
        proto = self._reevaluate(state, proto, acting)

        # honest production: height = slot index (:370-377)
        produce = tbp & (slot_now < mh)
        proto, em_b = self._build_blocks(
            state, proto, produce, proto["head"], jnp.broadcast_to(slot_now, (n,))
        )
        emissions.append(em_b)

        if self.byz_variant == "wf":
            # WF kick-off build: block 1 on genesis (reevaluateH at genesis)
            proto, em_k = self._build_blocks(
                state,
                proto,
                wf_kick,
                jnp.zeros(n, jnp.int32),
                jnp.ones(n, jnp.int32),
            )
            emissions.append(em_k)

            # ---- 6. WF scheduled build lands (r(), :663-668) --------------
            twfb = jnp.zeros(n, bool).at[to].max(is_twfb, mode="drop")
            wf_base = jnp.zeros(n, jnp.int32).at[to].max(
                jnp.where(is_twfb, pay0, 0), mode="drop"
            )
            wf_th = jnp.zeros(n, jnp.int32).at[to].max(
                jnp.where(is_twfb, pay1, 0), mode="drop"
            )
            proto, em_w = self._build_blocks(
                state, proto, twfb & (wf_th < mh), wf_base, wf_th
            )
            emissions.append(em_w)
        else:
            # ---- 6'. byz producer fires (reevaluateH + variant head tweak
            # + build, CasperIMD.java:529-542 + :285-300/:318-327/:342-356)
            th = proto["wf_to_send"]
            hr2 = jnp.arange(mh, dtype=jnp.int32)
            # deepest ancestor of head strictly below toSend (the
            # while-head.height>=toSend parent walk)
            head_oh2 = jax.nn.one_hot(proto["head"], mh, dtype=bool)
            cand = (proto["anc"][proto["head"]] | head_oh2) & (
                hr2[None, :] < th[:, None]
            )
            base = jnp.max(jnp.where(cand, hr2[None, :], 0), axis=1).astype(jnp.int32)
            direct = base == th - 1
            if self.byz_variant == "sf":
                # skip the direct father to steal its transactions
                skip = tbyz & (base != 0) & direct
                base = jnp.where(
                    skip, jnp.clip(proto["blk_parent"][base], 0, mh - 1), base
                )
                proto["byz_direct"] = proto["byz_direct"] + (tbyz & skip).astype(jnp.int32)
                proto["byz_older"] = proto["byz_older"] + (tbyz & ~skip).astype(jnp.int32)
            elif self.byz_variant == "ns":
                # skip the father when the father skipped the grandfather
                gp = jnp.clip(proto["blk_parent"][base], 0, mh - 1)
                cond = (
                    tbyz
                    & (base != 0)
                    & direct
                    & (gp == th - 3)
                    & proto["seen"][ids, jnp.clip(th - 2, 0, mh - 1)]
                    & proto["blk_exists"][jnp.clip(th - 2, 0, mh - 1)]
                )
                base = jnp.where(cond, jnp.clip(th - 2, 0, mh - 1), base)
                proto["byz_skipped"] = proto["byz_skipped"] + cond.astype(jnp.int32)
            else:  # plain delay: counters only
                proto["byz_direct"] = proto["byz_direct"] + (tbyz & direct).astype(
                    jnp.int32
                )
                proto["byz_older"] = proto["byz_older"] + (tbyz & ~direct).astype(
                    jnp.int32
                )
            proto, em_z = self._build_blocks(
                state, proto, tbyz & (th < mh), base, th
            )
            emissions.append(em_z)
            proto["wf_to_send"] = jnp.where(tbyz, th + self.bpc, proto["wf_to_send"])
            emissions.append(
                Emission(  # re-arm the byz beat
                    mask=tbyz,
                    from_idx=ids,
                    to_idx=ids,
                    mtype=self.mtype("TBYZ"),
                    payload=jnp.zeros((n, 2), jnp.int32),
                    arrival=jnp.broadcast_to(
                        t + SLOT_DURATION * self.bpc, (n,)
                    ).astype(jnp.int32),
                )
            )

        # attester votes: create the attestation and broadcast it ------------
        vote_h = slot_now
        can_vote = tatt & (vote_h >= 1) & (vote_h < mh)
        att_slot = jnp.clip(
            (vote_h - 1) * self.apr + jnp.where(self.is_att, self._att_j(), 0),
            0,
            ma - 1,
        )
        w_a = jnp.where(can_vote, att_slot, ma)
        proto["att_exists"] = proto["att_exists"].at[w_a].set(True, mode="drop")
        proto["att_head"] = proto["att_head"].at[w_a].set(proto["head"], mode="drop")
        # the attester holds its own attestation from the start
        proto["rec_att"] = proto["rec_att"].at[ids, w_a].set(True, mode="drop")
        # committee of this slot shares the tick: [apr x N] rows
        cm = self.committee[jnp.clip((vote_h - 1) % self.cl, 0, self.cl - 1)]
        cm_mask = can_vote[cm]  # [apr]
        emissions.append(
            Emission(
                mask=jnp.repeat(cm_mask, n),
                from_idx=jnp.repeat(cm, n),
                to_idx=jnp.tile(ids, self.apr),
                mtype=self.mtype("ATT"),
                payload=jnp.stack(
                    [
                        jnp.repeat(att_slot[cm], n),
                        jnp.zeros(self.apr * n, jnp.int32),
                    ],
                    axis=1,
                ),
                send_time=jnp.broadcast_to(
                    t + p.attestation_construction_time, (self.apr * n,)
                ).astype(jnp.int32),
            )
        )

        return state._replace(proto=proto), emissions

    def _att_j(self):
        """Attester committee-member index (i // cycle_length)."""
        j = jnp.zeros(self.n_nodes, jnp.int32)
        return j.at[self.att_ids].set(self.att_cidx)

    def all_done(self, state):
        return jnp.asarray(False)  # open-ended, like the oracle

    def head_height(self, state):
        return state.proto["head"]


def make_casper(
    params: Optional[CasperParameters] = None,
    max_heights: int = 24,
    capacity: Optional[int] = None,
    seed: int = 0,
    byz_variant: str = "wf",
    byz_delay: int = 0,
):
    """Host-side construction from the oracle's init (observer + the chosen
    Byzantine producer variant + honest producers + attesters, same RNG).
    byz_variant selects node 0's producer: "wf" (default,
    ByzBlockProducerWF), "delay", "sf", "ns" (CasperIMD.java:511-707)."""
    params = params or CasperParameters()
    oracle = CasperIMD(params)
    from .casper import (
        ByzBlockProducer,
        ByzBlockProducerNS,
        ByzBlockProducerSF,
        ByzBlockProducerWF,
    )

    byz_cls = {
        "wf": ByzBlockProducerWF,
        "delay": ByzBlockProducer,
        "sf": ByzBlockProducerSF,
        "ns": ByzBlockProducerNS,
    }[byz_variant]
    oracle.init(byz_cls(oracle, byz_delay, oracle.genesis))
    nodes = oracle.network().all_nodes
    n = len(nodes)
    att_ids = np.array(
        [nd.node_id for nd in nodes if isinstance(nd, Attester)], np.int32
    )
    is_bp = np.array(
        [
            isinstance(nd, BlockProducer)
            and nd is not oracle.bps[0]
            for nd in nodes
        ]
    )
    cl, apr = params.cycle_length, params.attesters_per_round
    committee = np.zeros((cl, apr), np.int32)
    for idx, aid in enumerate(att_ids):
        committee[idx % cl, idx // cl] = aid
    roles = {
        "n_nodes": n,
        "is_att": np.array([isinstance(nd, Attester) for nd in nodes]),
        "is_bp": is_bp,
        "bp0": oracle.bps[0].node_id,
        "att_ids": att_ids,
        "att_cidx": np.arange(len(att_ids), dtype=np.int32) // cl,
        "committee": committee,
        "prod_ids": np.array([nd.node_id for nd in oracle.bps], np.int32),
    }
    latency = registry_network_latencies.get_by_name(params.network_latency_name)
    city_index = getattr(latency, "city_index", None)
    cols = build_node_columns(nodes, city_index)
    proto = BatchedCasper(params, roles, max_heights, byz_variant, byz_delay)
    if capacity is None:
        # the peak in-flight load is one committee's attestation broadcast
        # ([apr x N] messages, all delivered well inside the 8 s slot) plus
        # scheduled self-messages; a full ring DROPS new sends, so auto-size
        # to 1.5 waves (the default 20x4 config keeps the old 1<<14)
        wave = apr * n + 4 * n
        capacity = max(1 << 14, 1 << int(np.ceil(np.log2(1.5 * wave))))
    # flat mode (wheel_rows=0): Casper's scheduling is dominated by
    # explicit-arrival self-messages whole 8 s slots ahead — far beyond any
    # useful wheel horizon, so the exact overflow-lane scan IS the store
    net = BatchedNetwork(proto, latency, n, capacity=capacity, wheel_rows=0)
    state = net.init_state(cols, seed=seed, proto=proto.proto_init(n))
    return net, state

"""Slush: one round of the Avalanche family — repeated random sampling with
an alpha threshold, M rounds per node.

Reference semantics: protocols/Slush.java (color flip at `> A*K` and the
M-round counter :161-176; shared machinery in `_avalanche`).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

from ..core.params import WParameters, register_protocol
from ..core.registries import registry_network_latencies, registry_node_builders
from ..oracle.network import Network, Protocol
from ._avalanche import AvalancheNode, color_play, init_two_colors


@dataclasses.dataclass
class SlushParameters(WParameters):
    nodes_av: int = 100
    m: int = 4  # number of rounds; grows logarithmically with n
    k: int = 7  # sample size
    a: float = 4.0  # alpha threshold
    node_builder_name: Optional[str] = None
    network_latency_name: Optional[str] = None

    @property
    def ak(self) -> float:
        return self.k * self.a


class SlushNode(AvalancheNode):
    __slots__ = ("round",)

    def __init__(self, p: "Slush"):
        super().__init__(p)
        self.round = 0

    def on_answer(self, query_id: int, color: int) -> None:
        """After K answers: flip if the other color got > A*K of them; keep
        querying while round < M (Slush.java:161-176)."""
        p = self._p
        asw = self.answer_ip[query_id]
        asw.colors_found[color] += 1
        if asw.answer_count() == p.params.k:
            del self.answer_ip[query_id]
            if asw.colors_found[self._other_color()] > p.params.ak:
                self.my_color = self._other_color()
            if self.round < p.params.m:
                self.round += 1
                self.send_query(asw.round + 1)


@register_protocol("Slush", SlushParameters)
class Slush(Protocol):
    def __init__(self, params: SlushParameters):
        self.params = params
        self._network: Network[SlushNode] = Network()
        self.nb = registry_node_builders.get_by_name(params.node_builder_name)
        self._network.set_network_latency(
            registry_network_latencies.get_by_name(params.network_latency_name)
        )

    def init(self) -> None:
        init_two_colors(self, SlushNode)

    def network(self) -> Network:
        return self._network

    def copy(self) -> "Slush":
        return Slush(self.params)

    def __str__(self) -> str:
        return (
            f"Slush{{Nodes={self.params.nodes_av}, latency={self._network.network_latency}, "
            f"M={self.params.m}, AK={self.params.ak}}}"
        )

    def play(self, graph_path: Optional[str] = None, verbose: bool = False):
        """Scenario driver (Slush.java:222-268)."""
        m = self.params.m
        return color_play(self, lambda gn: gn.round < m, graph_path, verbose)


def main():
    Slush(SlushParameters(100, 5, 7, 4.0 / 7.0, None, None)).play(
        graph_path="graph.png", verbose=True
    )


if __name__ == "__main__":
    main()

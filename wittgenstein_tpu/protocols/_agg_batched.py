"""Shared machinery for batched bitset-aggregation protocols (Handel, GSF).

Both protocols keep per-node contribution bitsets in the XOR-relative
layout (ops.bitops): bit j of node i's vector is node i^j, level l is the
static bit block [2^(l-1), 2^l), and re-addressing sender s's level-l
content into receiver i's space is the bit permutation j -> j ^ r0 with
r0 = (i^s) & (2^(l-1)-1).

The in-flight message channel is the finite-shape stand-in for the
oracle's per-ms message queue: per (receiver, level), D arrival-keyed
slots (earliest arrival wins; slot = arrival mod D) plus one freshest-
offer backstop slot that is always overwritten by the newest send — so
when a level's traffic dies out, the last content a laggard was offered
still delivers instead of being displaced.  Content is stored in the
RECEIVER's block-local bit space: the xor_shuffle re-addressing runs at
SEND time over the send rows (sparse — dissemination fires once per
period) instead of at delivery over every (level, slot) cell every tick,
which measured ~9x less shuffle work and took _channel_deliver from 80%
of the tick to a minority share.  Displacements (an ok send that wins
neither slot, or evicts a still-pending occupant) are counted in
proto["displaced"] — the channel analog of SimState.dropped.

Program-size design (the r4 rewrite): levels are grouped into WIDTH
BUCKETS — consecutive levels whose word width w_l = max(1, 2^(l-1)/32)
falls in the same class {1}, {2,4}, {8,16}, {32,64}, ... — and every
per-level computation runs once per BUCKET on a stacked [N, nl, ...]
level axis (w padded to the bucket max) instead of once per level.
Per-bucket channel/candidate content lives in flat 2D arrays
[N, nl*slots*w_pad] (large minor dims dodge XLA's (8,128) tile padding),
and block views of the full-width state vectors are pure
reshape/concat/shift pipelines — no gathers or scatters.  At 4096 nodes
this turns ~12 unrolled per-level bodies x 4 phases (plus ~24 per-level
send calls at ~700 StableHLO lines each) into ~4 bucket bodies and 2
stacked sends, which is what lets the flagship config compile.

Keys pack (absolute_arrival << rel_bits) | rel — no per-tick countdown
(see _advance_channel) — which bounds a sim at 2^(31-rel_bits) ms
(524 s at 4096 nodes; sends beyond it are dropped into the displaced
counter).  Node counts are capped at MAX_NODES = 2^14; construction
fails loudly beyond that.
"""

from __future__ import annotations

import dataclasses

import jax.numpy as jnp
import numpy as np
from jax import lax

from ..engine import BatchedProtocol
from ..ops.bitops import lowest_set_bit, popcount_words, xor_shuffle

INT32_MAX = np.int32(2**31 - 1)
MAX_NODES = 1 << 14  # int32 key-packing headroom


@dataclasses.dataclass(frozen=True)
class Bucket:
    """A run of consecutive levels sharing one padded word width."""

    levels: tuple  # level numbers, ascending
    w_pad: int  # padded width (max exact width in the bucket)

    @property
    def lo(self) -> int:
        return self.levels[0]

    @property
    def hi(self) -> int:
        return self.levels[-1]

    @property
    def nl(self) -> int:
        return len(self.levels)


"""Bucket grouping is by EXACT width (the PR-11 density pass): levels of
equal word width share a bucket (the sub-word levels, all w=1), and wider
levels get their own — so w_pad always equals the levels' exact width and
the channel/candidate arrays carry zero padding words.  The r4 rewrite
grouped width CLASSES ({2,4}, {8,16}, ...) instead, paying up to 2x
padding per bucket to halve the bucket count; with per-bucket bodies now
a minority of compile time, the padding was pure HBM waste (13.4 MB of
the 4096-node flagship's 124 MiB/replica).  Every phase iterates
`self.buckets` generically, so the regrouping is a pure layout change —
per-level arithmetic is untouched and results are bit-identical (padding
words were always zero)."""


class BitsetAggBase(BatchedProtocol):
    TICK_INTERVAL = 1  # verification capacity is modeled per-ms
    PAYLOAD_WIDTH = 0  # messaging bypasses the generic ring entirely
    CHANNEL_DEPTH = 8  # D: arrival-keyed in-flight slots per (receiver, level)
    BEAT_SEND_CALLS = 1  # _dissemination makes one stacked send

    def tick_beat(self, net, state):
        """Periodic dissemination as the engine's beat hook (subclasses
        implement _dissemination with exactly ONE stacked send, matching
        BEAT_SEND_CALLS; it commutes with _select — no shared proto keys,
        order-independent channel competition).  Wrapped in the
        NARROW_LEAVES widen/narrow boundary (identity for declarers of
        none, e.g. GSF) so the hook body computes on the int32 view."""
        state = state._replace(proto=self.widen_proto(state.proto))
        state = self._dissemination(net, state)
        return state._replace(proto=self.narrow_proto(state.proto))

    def _init_geometry(self, n: int) -> None:
        if n & (n - 1):
            raise ValueError("power-of-two node counts only")
        if n > MAX_NODES:
            raise NotImplementedError(
                f"node_count {n} > {MAX_NODES}: int32 channel/sort key packing "
                "would overflow; widen the keys before raising this cap"
            )
        self.n_nodes = n
        self.n_words = max(1, n // 32)
        self.n_levels = n.bit_length()  # levels 0..log2(n)
        self.rel_bits = max(1, (n - 1).bit_length())
        self.MSG_TYPES = [f"SIGS_L{l}" for l in range(self.n_levels)]

        # per-level content geometry: level l's payload is bits [0, 2^(l-1))
        # = w_l exact words; bs_l = block size in bits
        self.w = [0] * self.n_levels
        self.bs = [0] * self.n_levels
        for l in range(1, self.n_levels):
            self.bs[l] = 1 << (l - 1)
            self.w[l] = max(1, (1 << (l - 1)) // 32)
        self.w_max = self.w[self.n_levels - 1] if self.n_levels > 1 else 1

        # exact-width buckets over levels 1..L-1 (see module docstring):
        # consecutive levels of EQUAL width share a bucket, so w_pad is
        # always the exact width and no padding words are carried
        buckets = []
        for l in range(1, self.n_levels):
            if buckets and buckets[-1][1] == self.w[l]:
                buckets[-1][0].append(l)
            else:
                buckets.append([[l], self.w[l]])
        self.buckets = [Bucket(tuple(lv), wp) for lv, wp in buckets]

        # static per-level tables (stacked [L-1] vectors, level-1 at index 0)
        self.lv_w = np.asarray(self.w[1:], np.int32)  # exact widths
        self.lv_bs = np.asarray(self.bs[1:], np.int32)  # block sizes

    # -- stacked block views -------------------------------------------------
    # Full-width [.., W] layout is the concatenation of level blocks:
    # word 0 = bit 0 (level 0) + sub-word blocks of levels with bs < 32;
    # each level with bs >= 32 owns words [bs/32, 2bs/32).

    def _blocks(self, x, b: Bucket):
        """Bucket view of full-width vectors: [N, W] -> [N, nl, w_pad],
        zero above each level's exact width."""
        outs = []
        for l in b.levels:
            bs, w = self.bs[l], self.w[l]
            if bs < 32:
                blk = (x[..., 0:1] >> jnp.uint32(bs)) & jnp.uint32((1 << bs) - 1)
            else:
                blk = x[..., bs // 32 : (2 * bs) // 32]
            if w < b.w_pad:
                blk = jnp.concatenate(
                    [blk, jnp.zeros(blk.shape[:-1] + (b.w_pad - w,), jnp.uint32)],
                    axis=-1,
                )
            outs.append(blk)
        return jnp.stack(outs, axis=-2)  # [.., nl, w_pad]

    def _lows(self, x, b: Bucket):
        """Bucket view of sender-space outgoing content (bits [0, 2^(l-1)))
        per level: [N, W] -> [N, nl, w_pad], zero-padded."""
        outs = []
        for l in b.levels:
            bs, w = self.bs[l], self.w[l]
            if bs < 32:
                blk = x[..., 0:1] & jnp.uint32((1 << bs) - 1)
            else:
                blk = x[..., : bs // 32]
            if w < b.w_pad:
                blk = jnp.concatenate(
                    [blk, jnp.zeros(blk.shape[:-1] + (b.w_pad - w,), jnp.uint32)],
                    axis=-1,
                )
            outs.append(blk)
        return jnp.stack(outs, axis=-2)

    def _assemble(self, x_old, pieces):
        """Rebuild full-width vectors from per-bucket block stacks.

        pieces: list aligned with self.buckets of [N, nl, w_pad] (zero above
        exact widths).  Level-0's bit 0 is preserved from x_old."""
        word0 = x_old[..., 0] & jnp.uint32(1)
        tail = []
        for b, pc in zip(self.buckets, pieces):
            for j, l in enumerate(b.levels):
                bs, w = self.bs[l], self.w[l]
                blk = pc[..., j, :w]
                if bs < 32:
                    word0 = word0 | (blk[..., 0] << jnp.uint32(bs))
                else:
                    tail.append(blk)
        return jnp.concatenate([word0[..., None]] + tail, axis=-1)

    def _level_stats(self, per_bucket):
        """Concat per-bucket [N, nl] level-axis stats into [N, L-1]."""
        return jnp.concatenate(per_bucket, axis=-1)

    def _width_mask(self, b: Bucket):
        """bool[nl, w_pad]: word j valid for the bucket's level row."""
        return (
            np.arange(b.w_pad, dtype=np.int32)[None, :]
            < np.asarray([self.w[l] for l in b.levels], np.int32)[:, None]
        )

    def _dyn_low(self, x, level, b: Bucket):
        """Sender-space outgoing content at a DYNAMIC per-node level
        (valid where level is inside bucket b): [N, W], [N] -> [N, w_pad]."""
        lv = jnp.clip(level, 1, self.n_levels - 1) - 1
        bs = jnp.asarray(self.lv_bs)[lv]
        w = jnp.asarray(self.lv_w)[lv]
        out = x[..., : b.w_pad]
        if b.w_pad == 1 and self.bs[b.lo] < 32:
            # sub-word levels: bits [0, bs) of word 0 (bs may be 32; the
            # bs & 31 shift puts 0 in the lane the `full` select discards)
            m = (jnp.uint32(1) << (bs & 31).astype(jnp.uint32)) - 1
            m = jnp.where(bs >= 32, jnp.uint32(0xFFFFFFFF), m)
            return out & m[..., None]
        return out * (jnp.arange(b.w_pad, dtype=jnp.int32)[None, :] < w[..., None])

    # -- misc bit helpers (unchanged semantics) ------------------------------
    @staticmethod
    def _onehot(r0, w: int):
        """Block-local one-hot bit r0: [...] int32 -> [..., w] uint32."""
        word = r0 >> 5
        bit = (r0 & 31).astype(jnp.uint32)
        return jnp.where(
            jnp.arange(w, dtype=jnp.int32) == word[..., None],
            (jnp.uint32(1) << bit)[..., None],
            jnp.uint32(0),
        )

    @staticmethod
    def _lowest_bit(words):
        """Index of the lowest set bit over the last axis of packed [..., w]
        uint32 vectors (undefined when empty — gate on popcount > 0).
        Shared with the engine's wheel-occupancy scan (ops.bitops)."""
        return lowest_set_bit(words)

    def _getbit(self, x, pos):
        """Bit `pos` of full-width [N, W] vectors; pos is [N, ...] int32."""
        word = jnp.take_along_axis(
            x, (pos >> 5).reshape(pos.shape[0], -1), axis=1
        ).reshape(pos.shape)
        return (word >> (pos & 31).astype(jnp.uint32)) & jnp.uint32(1)

    # -- channel layout ------------------------------------------------------
    # in_key: [N, (L-1)*(D+1)] packed (arrival<<rel_bits | rel);
    # content per bucket i: proto[f"in_sig{i}"] = [N, nl*(D+1)*w_pad] flat,
    # level-major then slot then word.

    def _fresh_cols(self) -> np.ndarray:
        """bool[(L-1)*(D+1)]: which in_key columns are fresh-backstop slots."""
        ss = self.CHANNEL_DEPTH + 1
        cols = np.zeros((self.n_levels - 1) * ss, dtype=bool)
        cols[ss - 1 :: ss] = True
        return cols

    def _key_seg(self, in_key, l: int):
        ss = self.CHANNEL_DEPTH + 1
        return in_key[:, (l - 1) * ss : l * ss]

    def _keys_stacked(self, in_key):
        """[N, (L-1)*ss] -> [N, L-1, ss]."""
        ss = self.CHANNEL_DEPTH + 1
        return in_key.reshape(in_key.shape[0], self.n_levels - 1, ss)

    def _sig_view(self, proto, i: int, slots: int, prefix: str = "in_sig"):
        """Bucket i's content as [N, nl, slots, w_pad]."""
        b = self.buckets[i]
        a = proto[f"{prefix}{i}"]
        return a.reshape(a.shape[0], b.nl, slots, b.w_pad)

    def _channel_init(self, n: int):
        """Fresh in_key plus per-bucket in_sig arrays (fresh slots empty at
        -1, arrival slots at INT32_MAX)."""
        ss = self.CHANNEL_DEPTH + 1
        in_key = np.where(self._fresh_cols(), -1, INT32_MAX).astype(np.int32)
        sigs = {
            f"in_sig{i}": jnp.zeros((n, b.nl * ss * b.w_pad), jnp.uint32)
            for i, b in enumerate(self.buckets)
        }
        return (
            jnp.asarray(np.broadcast_to(in_key, (n, in_key.size)).copy()),
            sigs,
        )

    def _advance_channel(self, in_key, t):
        """Due mask at tick t; returns (in_key, due, empty_tpl).

        Keys pack the ABSOLUTE arrival (r5): the r4 relative packing
        needed a full read-modify-write of the key array every tick just
        to count down — at 4096 nodes x 32 replicas that decrement alone
        was ~450 MB/tick of pure HBM traffic.  Absolute keys keep every
        ordering property (min = earliest arrival, fresh-slot max =
        newest offer) and make the due test a compare against t."""
        occupied = (in_key >= 0) & (in_key != INT32_MAX)
        due = occupied & ((in_key >> self.rel_bits) <= t)
        empty_tpl = jnp.asarray(
            np.where(self._fresh_cols(), -1, INT32_MAX), jnp.int32
        )
        return in_key, due, empty_tpl

    # -- due-slot gather ------------------------------------------------------
    # Arrival slots are keyed slot = arrival mod D and a slot is due exactly
    # at its arrival tick, so at tick t the ONLY slots that can be due are
    # arrival slot (t mod D) and the fresh backstop.  Delivery therefore
    # gathers those two columns instead of processing all D+1 — the merge
    # runs at [K+2] instead of [K+D+1] width (pinned by
    # tests/test_agg_buckets.py::test_only_two_slots_can_be_due).

    def _due_pair_keys(self, keys3, due3, t):
        """[N, L-1, ss] stacked keys/due -> the two due-able columns as
        [N, L-1, 2] (index 0 = arrival slot t mod D, 1 = fresh)."""
        sidx = lax.rem(t, jnp.int32(self.CHANNEL_DEPTH))
        k_arr = lax.dynamic_index_in_dim(keys3, sidx, axis=2, keepdims=False)
        d_arr = lax.dynamic_index_in_dim(due3, sidx, axis=2, keepdims=False)
        d = self.CHANNEL_DEPTH
        return (
            jnp.stack([k_arr, keys3[:, :, d]], axis=2),
            jnp.stack([d_arr, due3[:, :, d]], axis=2),
        )

    def _due_pair_sig(self, proto, i: int, t, prefix: str = "in_sig"):
        """Bucket i's content for the two due-able slots: [N, nl, 2, w_pad]
        in receiver block-local space."""
        sig = self._sig_view(proto, i, self.CHANNEL_DEPTH + 1, prefix=prefix)
        sidx = lax.rem(t, jnp.int32(self.CHANNEL_DEPTH))
        s_arr = lax.dynamic_index_in_dim(sig, sidx, axis=2, keepdims=False)
        return jnp.stack([s_arr, sig[:, :, self.CHANNEL_DEPTH]], axis=2)

    # -- the stacked send path -----------------------------------------------
    def _send_stacked(self, net, state, mask, from_idx, to_idx, level, content, aux=None):
        """Send M messages (one per row, each at its own level) into the
        per-(receiver, level, slot) channel in ONE body: earliest arrival
        wins an arrival slot, the newest offer always takes the fresh slot.

        mask/from_idx/to_idx/level: [M] (level in [1, L-1]); content: list
        aligned with self.buckets of [M, w_pad] SENDER-space words (only
        rows whose level lies in the bucket need valid values) — they are
        re-addressed into the receiver's block-local space here, at send
        time; aux: optional [M] int32 stored per slot in proto["in_aux"].
        """
        proto = state.proto
        d = self.CHANNEL_DEPTH
        ss = d + 1
        # masked rows may carry junk levels; clamp so every computed index
        # is in range (their scatters are dropped via the n_nodes row)
        level = jnp.clip(level.astype(jnp.int32), 1, self.n_levels - 1)
        state, ok, arrival = net.latency_arrivals(
            state, mask, from_idx, to_idx, state.time + 1, level
        )
        # receiver traffic counters tick here, at send time: every ok send
        # is delivered by the oracle (Network.java:611-612), but the channel
        # may displace it — counting at send keeps end-of-run totals exact
        # at the cost of counters leading arrivals by the latency
        okc = ok.astype(jnp.int32)
        sizes = jnp.asarray(self._size_table(), jnp.int32)[level]
        state = state._replace(
            msg_received=state.msg_received.at[to_idx].add(okc, mode="drop"),
            bytes_received=state.bytes_received.at[to_idx].add(
                okc * sizes, mode="drop"
            ),
        )
        rel = (to_idx ^ from_idx).astype(jnp.int32)
        # ABSOLUTE arrival packing (no per-tick countdown — see
        # _advance_channel).  Sims running past the int32 packing horizon
        # (2^(31-rel_bits) ms: 524 s at 4096 nodes, 128 s at the 16384
        # cap) would overflow the shift; such sends are dropped and
        # counted in proto["displaced"] so a too-long sim fails loudly in
        # the displacement stats rather than corrupting arrival order.
        # strictly below the last in-horizon ms: at the boundary arrival,
        # a max-rel send would pack to exactly INT32_MAX — the empty-slot
        # sentinel — and vanish uncounted
        fits_t = arrival < (jnp.int32(1) << (31 - self.rel_bits)) - 1
        time_overflow = jnp.sum((ok & ~fits_t).astype(jnp.int32))
        ok = ok & fits_t
        key = jnp.where(ok, (arrival << self.rel_bits) | rel, INT32_MAX)

        slot = lax.rem(arrival, jnp.int32(d))

        # re-address sender-space content into the receiver's block-local
        # space (bit j -> j ^ r0) for ALL rows, shared by both commit
        # paths; r0 < bs keeps the permutation inside the level block, and
        # rows outside the bucket are zeroed so the (dropped) shuffle
        # can't gather out of range
        bs_row = jnp.asarray(self.lv_bs)[level - 1]  # [M] level block sizes
        cnt_list = []
        for i, b in enumerate(self.buckets):
            in_b = (level >= b.lo) & (level <= b.hi)
            r0 = jnp.where(in_b, rel & (bs_row - 1), 0)
            cnt_list.append(xor_shuffle(content[i].astype(jnp.uint32), r0))

        mesh = getattr(net, "node_mesh", None)
        if mesh is not None:
            # node-axis sharding: the channel commit goes through an
            # explicit all_to_all exchange of update rows so the channel
            # shards never gather
            return self._channel_commit_sharded(
                mesh, net.node_axis, state, ok, to_idx, level, key, slot,
                cnt_list, aux,
                cap=getattr(net, "exchange_capacity", None),
                time_overflow=time_overflow,
            )

        col = (level - 1) * ss + slot
        safe_to = jnp.where(ok, to_idx, self.n_nodes)
        prev = proto["in_key"].at[to_idx, col].get(mode="fill", fill_value=INT32_MAX)
        new_key = proto["in_key"].at[safe_to, col].min(key, mode="drop")
        winner = ok & (new_key[to_idx, col] == key)

        # freshest-offer backstop (empty at -1 so any real key wins the max)
        fcol = (level - 1) * ss + d
        new_key = new_key.at[safe_to, fcol].max(jnp.where(ok, key, -1), mode="drop")
        fresh_win = ok & (new_key[to_idx, fcol] == key)

        # displacement accounting (the channel's SimState.dropped analog):
        # an ok send that won neither slot, or a winner that evicted a
        # still-pending occupant with a later arrival
        lost_entry = ok & ~winner & ~fresh_win
        evicted = winner & (prev != INT32_MAX) & (prev > key)
        displaced = (
            jnp.sum((lost_entry | evicted).astype(jnp.int32)) + time_overflow
        )

        updates = dict(proto, in_key=new_key, displaced=proto["displaced"] + displaced)

        win_to = jnp.where(winner, to_idx, self.n_nodes)
        fwin_to = jnp.where(fresh_win, to_idx, self.n_nodes)
        for i, b in enumerate(self.buckets):
            in_b = (level >= b.lo) & (level <= b.hi)
            li = level - b.lo  # level row inside the bucket
            cw = jnp.arange(b.w_pad, dtype=jnp.int32)
            cols = ((li * ss + slot) * b.w_pad)[:, None] + cw
            fcols = ((li * ss + d) * b.w_pad)[:, None] + cw
            cnt = cnt_list[i]  # receiver-space content (hoisted above)
            a = updates[f"in_sig{i}"]
            a = a.at[jnp.where(in_b, win_to, self.n_nodes)[:, None], cols].set(
                cnt, mode="drop"
            )
            a = a.at[jnp.where(in_b, fwin_to, self.n_nodes)[:, None], fcols].set(
                cnt, mode="drop"
            )
            updates[f"in_sig{i}"] = a
        if aux is not None:
            new_aux = proto["in_aux"].at[win_to, col].set(
                aux.astype(jnp.int32), mode="drop"
            )
            new_aux = new_aux.at[fwin_to, fcol].set(aux.astype(jnp.int32), mode="drop")
            updates["in_aux"] = new_aux
        return state._replace(proto=updates)

    # -- node-sharded channel commit (explicit all_to_all exchange) ----------
    def _channel_commit_sharded(
        self, mesh, axis, state, ok, to_idx, level, key, slot, cnt_list, aux,
        cap=None, time_overflow=0,
    ):
        """The channel commit of _send_stacked under node-axis sharding
        (SURVEY §7 / VERDICT r4 #4): each device owns N/P node rows of the
        channel arrays; update rows are BUCKETED BY DESTINATION DEVICE and
        exchanged with ONE lax.all_to_all per tensor, then committed with
        the same min/max-scatter semantics on the LOCAL shard.  GSPMD's
        alternative for these computed-index scatters is gathering the
        operand — which un-shards exactly the arrays this axis exists to
        split.  Bit-identical to the unsharded commit when cap is None:
        keys are unique per (receiver, level, rel), so winner selection is
        order-free, and the default per-destination bucket capacity is the
        full local row count (no overflow, nothing dropped).

        Exchange cost per device per send: meta [P, cap, 6] int32 +
        content [P, cap, w_pad] u32 per bucket.  The default cap = M/P
        makes the per-device transient the full global M rows (P x the
        resident sender rows) — fine for small meshes, quadratic-feeling
        at large P.  `cap` (engine attr `exchange_capacity`) bounds it;
        destinations are hash-spread so a few x the mean fan-in suffices,
        and bucket overflow is counted in proto["displaced"] — the same
        bounded-loss semantics as channel displacement, which the
        protocols' periodic re-offers are already designed to absorb
        (bit identity then becomes distribution parity)."""
        from functools import partial as _partial

        from jax import lax as _lax
        from jax.sharding import PartitionSpec as _P

        try:  # jax >= 0.8
            from jax import shard_map as _shard_map
        except ImportError:  # pragma: no cover
            from jax.experimental.shard_map import shard_map as _shard_map

        import inspect

        # the replication-check kwarg was renamed check_rep -> check_vma;
        # pick whichever this jax accepts
        _sig = inspect.signature(_shard_map).parameters
        _check_kw = {
            "check_vma" if "check_vma" in _sig else "check_rep": False
        }

        proto = state.proto
        n, d = self.n_nodes, self.CHANNEL_DEPTH
        ss = d + 1
        L = self.n_levels
        nb = len(self.buckets)
        p_sz = mesh.shape[axis]
        if n % p_sz:
            raise ValueError(f"n_nodes {n} not divisible by mesh axis {p_sz}")
        n_loc = n // p_sz
        have_aux = aux is not None
        aux_col = aux.astype(jnp.int32) if have_aux else jnp.zeros_like(to_idx)
        meta = jnp.stack(
            [to_idx, level, key, slot, aux_col, ok.astype(jnp.int32)], axis=1
        )  # [M, 6]

        sig_names = [f"in_sig{i}" for i in range(nb)]
        w_pads = [b.w_pad for b in self.buckets]

        in_specs = (
            [_P(axis)]  # meta rows
            + [_P(axis)] * nb  # content rows
            + [_P(axis)]  # in_key
            + [_P(axis)] * nb  # in_sig
            + ([_P(axis)] if have_aux else [])
        )
        out_specs = (
            [_P(axis)] + [_P(axis)] * nb + ([_P(axis)] if have_aux else []) + [_P()]
        )

        @_partial(
            _shard_map,
            mesh=mesh,
            in_specs=tuple(in_specs),
            out_specs=tuple(out_specs),
            **_check_kw,
        )
        def island(meta_l, *rest):
            cnts = rest[:nb]
            ikey = rest[nb]
            sigs = list(rest[nb + 1 : nb + 1 + nb])
            iaux = rest[nb + 1 + nb] if have_aux else None
            di = _lax.axis_index(axis)
            m_loc = meta_l.shape[0]
            bucket_cap = m_loc if cap is None else min(int(cap), m_loc)

            # 1. bucket local rows by destination device (invalid -> p_sz,
            # dropped by the scatter; beyond-capacity rows too, counted
            # below as displaced)
            dest = jnp.where(meta_l[:, 5] > 0, meta_l[:, 0] // n_loc, p_sz)
            order = jnp.argsort(dest)
            dsort = dest[order]
            pos = jnp.arange(m_loc, dtype=jnp.int32) - jnp.searchsorted(
                dsort, dsort, side="left"
            ).astype(jnp.int32)
            overflow = jnp.sum(
                ((pos >= bucket_cap) & (dsort < p_sz)).astype(jnp.int32)
            )

            def to_buf(vals, fill):
                buf = jnp.full(
                    (p_sz, bucket_cap) + vals.shape[1:], fill, vals.dtype
                )
                return buf.at[dsort, jnp.where(pos < bucket_cap, pos, bucket_cap)].set(
                    vals[order], mode="drop"
                )

            # 2. one all_to_all per tensor: device j's bucket-for-me lands
            # in my row j
            meta_x = _lax.all_to_all(
                to_buf(meta_l, 0), axis, split_axis=0, concat_axis=0, tiled=True
            ).reshape(p_sz * bucket_cap, 6)
            cnt_x = [
                _lax.all_to_all(
                    to_buf(c, 0), axis, split_axis=0, concat_axis=0, tiled=True
                ).reshape(p_sz * bucket_cap, w)
                for c, w in zip(cnts, w_pads)
            ]

            # 3. local commit — the unsharded scatter code with local
            # receiver rows (buffer fill rows have ok=0 and are masked)
            to_r = meta_x[:, 0] - di * n_loc
            lvl = jnp.clip(meta_x[:, 1], 1, L - 1)
            key_r = meta_x[:, 2]
            slot_r = meta_x[:, 3]
            aux_r = meta_x[:, 4]
            ok_r = meta_x[:, 5] > 0
            col = (lvl - 1) * ss + slot_r
            fcol = (lvl - 1) * ss + d
            safe_to = jnp.where(ok_r, to_r, n_loc)
            prev = ikey.at[safe_to, col].get(mode="fill", fill_value=INT32_MAX)
            new_key = ikey.at[safe_to, col].min(
                jnp.where(ok_r, key_r, INT32_MAX), mode="drop"
            )
            got = new_key.at[safe_to, col].get(mode="fill", fill_value=INT32_MAX)
            winner = ok_r & (got == key_r)
            new_key = new_key.at[safe_to, fcol].max(
                jnp.where(ok_r, key_r, -1), mode="drop"
            )
            fgot = new_key.at[safe_to, fcol].get(mode="fill", fill_value=-1)
            fresh_win = ok_r & (fgot == key_r)
            lost_entry = ok_r & ~winner & ~fresh_win
            evicted = winner & (prev != INT32_MAX) & (prev > key_r)
            displaced = jnp.sum((lost_entry | evicted).astype(jnp.int32))

            for i, b in enumerate(self.buckets):
                in_b = (lvl >= b.lo) & (lvl <= b.hi) & ok_r
                li = lvl - b.lo
                cw = jnp.arange(b.w_pad, dtype=jnp.int32)
                cols = ((li * ss + slot_r) * b.w_pad)[:, None] + cw
                fcols = ((li * ss + d) * b.w_pad)[:, None] + cw
                win_to = jnp.where(winner & in_b, to_r, n_loc)
                fwin_to = jnp.where(fresh_win & in_b, to_r, n_loc)
                sigs[i] = sigs[i].at[win_to[:, None], cols].set(
                    cnt_x[i], mode="drop"
                )
                sigs[i] = sigs[i].at[fwin_to[:, None], fcols].set(
                    cnt_x[i], mode="drop"
                )
            outs = [new_key] + sigs
            if have_aux:
                iaux = iaux.at[jnp.where(winner, to_r, n_loc), col].set(
                    aux_r, mode="drop"
                )
                iaux = iaux.at[jnp.where(fresh_win, to_r, n_loc), fcol].set(
                    aux_r, mode="drop"
                )
                outs.append(iaux)
            outs.append(_lax.psum(displaced + overflow, axis))
            return tuple(outs)

        args = (
            [meta]
            + cnt_list
            + [proto["in_key"]]
            + [proto[k] for k in sig_names]
            + ([proto["in_aux"]] if have_aux else [])
        )
        res = island(*args)
        updates = dict(proto, in_key=res[0])
        for i, k in enumerate(sig_names):
            updates[k] = res[1 + i]
        if have_aux:
            updates["in_aux"] = res[1 + nb]
        updates["displaced"] = proto["displaced"] + res[-1] + time_overflow
        return state._replace(proto=updates)

    # -- entry-identity candidate clears (shared by the _select
    # write-backs of handel_batched and gsf_batched: see the
    # handel_batched._select docstring for the semantics) ------------------
    @staticmethod
    def _entry_clear(cur_id3, cur_card3, tgt_id3, tgt_card3, tgt_mask3):
        """[N, L-1, K] clear mask: current entries equal in (id,
        cardinality) to any masked target entry of the same level."""
        m = (
            (cur_id3[..., :, None] == tgt_id3[..., None, :])
            & (cur_card3[..., :, None] == tgt_card3[..., None, :])
            & tgt_mask3[..., None, :]
        )
        return jnp.any(m, axis=-1)

    @staticmethod
    def _remove_chosen(ids, id3, card3, lvl_idx, sel_id, sel_card, remove):
        """Clear the chosen entry from its level's CURRENT slots by (id,
        cardinality) identity; returns the updated [N, L-1, K] id array
        (non-removing rows write their row back unchanged)."""
        row_id = jnp.take_along_axis(id3, lvl_idx[:, None, None], axis=1)[:, 0]
        row_card = jnp.take_along_axis(card3, lvl_idx[:, None, None], axis=1)[:, 0]
        mrow = (
            remove[:, None]
            & (row_id == sel_id[:, None])
            & (row_card == sel_card[:, None])
        )
        return id3.at[ids, lvl_idx].set(jnp.where(mrow, INT32_MAX, row_id))

    def _size_table(self):
        return np.asarray(
            [self.msg_size(t) for t in range(self.n_levels)], np.int32
        )

    # -- channel content accessor --------------------------------------------
    def _arrived_blocks(self, proto, i: int):
        """Bucket i's in-flight content, already in receiver block-local
        space (re-addressed at send time by _send_stacked):
        [N, nl, ss, w_pad].  Slots that are not `due` may hold stale
        content — consumers gate on the key/rank validity."""
        return self._sig_view(proto, i, self.CHANNEL_DEPTH + 1)

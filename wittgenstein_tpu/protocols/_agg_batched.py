"""Shared machinery for batched bitset-aggregation protocols (Handel, GSF).

Both protocols keep per-node contribution bitsets in the XOR-relative
layout (ops.bitops): bit j of node i's vector is node i^j, level l is the
static bit block [2^(l-1), 2^l), and re-addressing sender s's level-l
content into receiver i's space is the bit permutation j -> j ^ r0 with
r0 = (i^s) & (2^(l-1)-1).

The in-flight message channel is the finite-shape stand-in for the
oracle's per-ms message queue: per (receiver, level), D arrival-keyed
slots (earliest arrival wins; slot = arrival mod D) plus one freshest-
offer backstop slot that is always overwritten by the newest send — so
when a level's traffic dies out, the last content a laggard was offered
still delivers instead of being displaced.  Content is stored in SENDER
bit space at the level's exact word width w_l = max(1, 2^(l-1)/32),
packed into one flat word axis (W_total = sum w_l) to dodge XLA's (8,128)
tile padding on small minor dimensions.

Keys pack ((arrival - now) << rel_bits) | rel and are decremented once
per tick, so the packing never overflows int32 for node counts up to
MAX_NODES = 2^14; construction fails loudly beyond that.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np
from jax import lax

from ..engine import BatchedProtocol
from ..ops.bitops import level_block_mask, popcount_words

INT32_MAX = np.int32(2**31 - 1)
MAX_NODES = 1 << 14  # int32 key-packing headroom


class BitsetAggBase(BatchedProtocol):
    TICK_INTERVAL = 1  # verification capacity is modeled per-ms
    PAYLOAD_WIDTH = 0  # messaging bypasses the generic ring entirely
    CHANNEL_DEPTH = 8  # D: arrival-keyed in-flight slots per (receiver, level)

    def _init_geometry(self, n: int) -> None:
        if n & (n - 1):
            raise ValueError("power-of-two node counts only")
        if n > MAX_NODES:
            raise NotImplementedError(
                f"node_count {n} > {MAX_NODES}: int32 channel/sort key packing "
                "would overflow; widen the keys before raising this cap"
            )
        self.n_nodes = n
        self.n_words = max(1, n // 32)
        self.n_levels = n.bit_length()  # levels 0..log2(n)
        self.rel_bits = max(1, (n - 1).bit_length())
        self.MSG_TYPES = [f"SIGS_L{l}" for l in range(self.n_levels)]

        # per-level content geometry: level l's payload is bits [0, 2^(l-1))
        # = w_l words at flat offset off_l
        self.w = [0] * self.n_levels
        self.off = [0] * self.n_levels
        acc = 0
        for l in range(1, self.n_levels):
            self.w[l] = max(1, (1 << (l - 1)) // 32)
            self.off[l] = acc
            acc += self.w[l]
        self.w_total = acc
        self.w_max = self.w[self.n_levels - 1] if self.n_levels > 1 else 1

        # static full-width level masks (receiver rel space)
        self.level_masks = np.stack(
            [level_block_mask(l, self.n_words) for l in range(self.n_levels)]
        )
        low = np.zeros_like(self.level_masks)
        acc_m = np.zeros(self.n_words, dtype=np.uint32)
        for l in range(self.n_levels):
            low[l] = acc_m  # bits below level l's block
            acc_m = acc_m | self.level_masks[l]
        self.low_masks = low

    # -- block-local helpers -------------------------------------------------
    # receiver rel space block [2^(l-1), 2^l) <-> block-local bits [0, 2^(l-1))
    def _blk(self, x, l: int):
        """Level-l block of full-width vectors [..., W] -> [..., w_l]."""
        bs = 1 << (l - 1)
        if bs >= 32:
            return x[..., bs // 32 : (2 * bs) // 32]
        return (x[..., 0:1] >> jnp.uint32(bs)) & jnp.uint32((1 << bs) - 1)

    def _blk_write(self, x, l: int, blk, where):
        """Write block-local [..., w_l] back into full-width [..., W]."""
        bs = 1 << (l - 1)
        if bs >= 32:
            new = jnp.where(where[..., None], blk, x[..., bs // 32 : (2 * bs) // 32])
            return x.at[..., bs // 32 : (2 * bs) // 32].set(new)
        m = jnp.uint32(((1 << bs) - 1) << bs)
        w0 = (x[..., 0] & ~m) | ((blk[..., 0] << jnp.uint32(bs)) & m)
        return x.at[..., 0].set(jnp.where(where, w0, x[..., 0]))

    def _low(self, x, l: int):
        """Sender-space outgoing content at level l: bits [0, 2^(l-1))."""
        bs = 1 << (l - 1)
        if bs >= 32:
            return x[..., : bs // 32]
        return x[..., 0:1] & jnp.uint32((1 << bs) - 1)

    @staticmethod
    def _onehot(r0, w: int):
        """Block-local one-hot bit r0: [...] int32 -> [..., w] uint32."""
        word = r0 >> 5
        bit = (r0 & 31).astype(jnp.uint32)
        return jnp.where(
            jnp.arange(w, dtype=jnp.int32) == word[..., None],
            (jnp.uint32(1) << bit)[..., None],
            jnp.uint32(0),
        )

    @staticmethod
    def _lowest_bit(words):
        """Index of the lowest set bit of packed [N, w] uint32 vectors
        (undefined when empty — gate on popcount > 0)."""
        word_nz = words != 0
        widx = jnp.argmax(word_nz, axis=1).astype(jnp.int32)
        wval = jnp.take_along_axis(words, widx[:, None], axis=1)[:, 0]
        lowbit = popcount_words(((wval & (-wval).astype(jnp.uint32)) - 1)[:, None])
        return widx * 32 + lowbit

    def _getbit(self, x, pos):
        """Bit `pos` of full-width [N, W] vectors; pos is [N, ...] int32."""
        word = jnp.take_along_axis(
            x, (pos >> 5).reshape(pos.shape[0], -1), axis=1
        ).reshape(pos.shape)
        return (word >> (pos & 31).astype(jnp.uint32)) & jnp.uint32(1)

    # -- channel layout ------------------------------------------------------
    def _fresh_cols(self) -> np.ndarray:
        """bool[(L-1)*(D+1)]: which in_key columns are fresh-backstop slots."""
        ss = self.CHANNEL_DEPTH + 1
        cols = np.zeros((self.n_levels - 1) * ss, dtype=bool)
        cols[ss - 1 :: ss] = True
        return cols

    def _key_seg(self, in_key, l: int):
        ss = self.CHANNEL_DEPTH + 1
        return in_key[:, (l - 1) * ss : l * ss]

    def _sig_seg(self, sig_flat, l: int, slots: int):
        n = sig_flat.shape[0]
        o, w = self.off[l] * slots, self.w[l] * slots
        return sig_flat[:, o : o + w].reshape(n, slots, self.w[l])

    def _channel_init(self, n: int):
        """Fresh in_key / in_sig arrays (fresh slots empty at -1, arrival
        slots at INT32_MAX)."""
        d = self.CHANNEL_DEPTH
        in_key = np.where(self._fresh_cols(), -1, INT32_MAX).astype(np.int32)
        return (
            jnp.asarray(np.broadcast_to(in_key, (n, in_key.size)).copy()),
            jnp.zeros((n, (d + 1) * self.w_total), jnp.uint32),
        )

    def _advance_channel(self, in_key):
        """Decrement occupied keys one tick; returns (in_key, due, empty_tpl)."""
        occupied = (in_key >= 0) & (in_key != INT32_MAX)
        in_key = jnp.where(occupied, in_key - (1 << self.rel_bits), in_key)
        due = occupied & ((in_key >> self.rel_bits) <= 0)
        empty_tpl = jnp.asarray(
            np.where(self._fresh_cols(), -1, INT32_MAX), jnp.int32
        )
        return in_key, due, empty_tpl

    # -- send path -----------------------------------------------------------
    def _send_level(self, net, state, l: int, mask, from_idx, to_idx, content, aux=None):
        """Send K messages at level l into the per-(receiver, slot) channel;
        earliest arrival wins an arrival slot, the newest offer always takes
        the fresh slot.  Content is sender-space [K, w_l]; `aux` is an
        optional [K] int32 side value stored per slot in proto["in_aux"]."""
        proto = state.proto
        d = self.CHANNEL_DEPTH
        state, ok, arrival = net.latency_arrivals(
            state, mask, from_idx, to_idx, state.time + 1, jnp.int32(l)
        )
        # receiver traffic counters tick here, at send time: every ok send
        # is delivered by the oracle (Network.java:611-612), but the channel
        # may displace it — counting at send keeps end-of-run totals exact
        # at the cost of counters leading arrivals by the latency
        okc = ok.astype(jnp.int32)
        state = state._replace(
            msg_received=state.msg_received.at[to_idx].add(okc, mode="drop"),
            bytes_received=state.bytes_received.at[to_idx].add(
                okc * self.msg_size(l), mode="drop"
            ),
        )
        rel = (to_idx ^ from_idx).astype(jnp.int32)
        # time-relative arrival (>= 1): decremented per tick, so the packing
        # never overflows int32
        rel_arr = arrival - state.time
        key = jnp.where(ok, (rel_arr << self.rel_bits) | rel, INT32_MAX)
        ss = d + 1

        slot = lax.rem(arrival, jnp.int32(d))
        col = (l - 1) * ss + slot
        safe_to = jnp.where(ok, to_idx, self.n_nodes)
        new_key = proto["in_key"].at[safe_to, col].min(key, mode="drop")
        winner = ok & (new_key[to_idx, col] == key)

        # freshest-offer backstop (empty at -1 so any real key wins the max)
        fcol = (l - 1) * ss + d
        new_key = new_key.at[safe_to, fcol].max(jnp.where(ok, key, -1), mode="drop")
        fresh_win = ok & (new_key[to_idx, fcol] == key)

        win_to = jnp.where(winner, to_idx, self.n_nodes)
        wcols = (ss * self.off[l] + slot[:, None] * self.w[l]) + jnp.arange(
            self.w[l], dtype=jnp.int32
        )
        new_sig = proto["in_sig"].at[win_to[:, None], wcols].set(
            content.astype(jnp.uint32), mode="drop"
        )
        fwin_to = jnp.where(fresh_win, to_idx, self.n_nodes)
        fwcols = (ss * self.off[l] + d * self.w[l]) + jnp.arange(
            self.w[l], dtype=jnp.int32
        )
        new_sig = new_sig.at[fwin_to[:, None], fwcols[None, :]].set(
            content.astype(jnp.uint32), mode="drop"
        )
        updates = dict(proto, in_key=new_key, in_sig=new_sig)
        if aux is not None:
            new_aux = proto["in_aux"].at[win_to, col].set(
                aux.astype(jnp.int32), mode="drop"
            )
            new_aux = new_aux.at[fwin_to, fcol].set(aux.astype(jnp.int32), mode="drop")
            updates["in_aux"] = new_aux
        return state._replace(proto=updates)

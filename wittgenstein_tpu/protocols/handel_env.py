"""BatchedAttackEnv: the Handel Byzantine attacker as a vectorized env.

The ethpow BatchedMinerEnv precedent (ethpow_env.py) turned the selfish
miner into R lockstep replicas stepping one jitted device program; this
module does the same for an IN-PROTOCOL Handel adversary, closing the
search package's second loop: the same optimizers that discover
FaultPlans (search/optimizers.py) attack a sequential adversary POLICY.

The adversary controls a fixed bloc of live aggregators (the top of the
live list, matching search.genome.FaultGenome's silence bloc).  At every
`decision_ms` boundary the policy chooses, per replica, whether the bloc
is SILENT for the coming step — withholding its signatures and relaying
nothing — or participates honestly.  Mechanically the toggle is pure
fault-lane data: the replica's Byzantine-silence window scalars flip
between [0, INT_MAX) (active) and [INT_MAX, ...) (never), so the
transition stays ONE jitted program for all R replicas and recompiles
for nothing — the policy's choices are state, exactly like the fault
sweep's schedules.

Reward is the ATTACKER's objective: the fraction of statically-live
nodes whose aggregation is still incomplete (higher = stronger attack),
matching the `reward_ratio` objective in search/objectives.py — so
`search.driver.optimize_env_policy(env)` optimizes silence-window
policies with the identical ask/tell machinery, one rollout generation
per batched pass, each replica carrying one candidate policy.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np


class BatchedAttackEnv:
    """R lockstep Handel-attacker environments in one device program."""

    def __init__(
        self,
        net=None,
        state=None,
        n_replicas: int = 8,
        decision_ms: int = 100,
        horizon_ms: int = 1000,
        n_silent: Optional[int] = None,
        seed: int = 0,
    ):
        from ..faults import FaultConfig
        from ..faults.state import INT_MAX

        if (net is None) != (state is None):
            raise ValueError("pass both of (net, state) or neither")
        if net is None:
            from ..core.registries import registry_batched_protocols

            net, state = registry_batched_protocols.get("handel").factory()
        if decision_ms <= 0:
            raise ValueError(f"decision_ms={decision_ms} must be positive")
        if horizon_ms % decision_ms != 0:
            raise ValueError(
                f"horizon_ms={horizon_ms} must be a multiple of "
                f"decision_ms={decision_ms}"
            )
        self.n_replicas = int(n_replicas)
        self.decision_ms = int(decision_ms)
        self.horizon_ms = int(horizon_ms)
        self.seed = int(seed)

        self.net, self._fstate = net.with_faults(state, FaultConfig())
        live = np.flatnonzero(~np.asarray(state.down))
        if n_silent is None:
            n_silent = max(1, len(live) // 5)
        if not 0 < n_silent <= len(live):
            raise ValueError(
                f"n_silent={n_silent} outside (0, live={len(live)}]"
            )
        # the adversary bloc: top of the live list, the same selection
        # FaultGenome._silence_nodes makes — so a policy discovered here
        # and a silence-lane FaultPlan talk about the same nodes
        self.silent_nodes = live[len(live) - int(n_silent):]
        self._states = None

        fnet, dms = self.net, self.decision_ms
        never = jnp.asarray(INT_MAX, jnp.int32)

        def transition(states, actions):
            on = actions.astype(bool)  # [R]: silent for this step?
            fs = states.faults._replace(
                byz_start=jnp.where(on, jnp.int32(0), never),
                byz_end=jnp.broadcast_to(never, on.shape),
            )
            return fnet._run_ms_batched_impl(
                states._replace(faults=fs), dms, False
            )

        self._transition = jax.jit(transition)

    # -- gym-style surface ---------------------------------------------------
    def _observe(self, states):
        down = np.asarray(states.down)
        done = np.asarray(states.done_at)
        live = ~down
        n_live = np.maximum(live.sum(axis=1), 1)
        done_frac = ((done > 0) & live).sum(axis=1) / n_live
        return {
            "time": np.asarray(states.time),
            "done_frac": done_frac,
            "undone_frac": 1.0 - done_frac,
            "msg_received_mean": np.where(
                live, np.asarray(states.msg_received), 0
            ).sum(axis=1)
            / n_live,
        }

    def reset(self):
        from ..engine.core import replicate_state

        st = self._fstate._replace(
            faults=self._fstate.faults._replace(
                byz_silent=jnp.zeros(self.net.n_nodes, bool)
                .at[jnp.asarray(self.silent_nodes)]
                .set(True)
            )
        )
        self._states = replicate_state(
            st,
            self.n_replicas,
            seeds=np.arange(self.seed, self.seed + self.n_replicas),
        )
        return self._observe(self._states)

    def step(self, actions):
        """actions: int/bool array [R] — 1 = adversary bloc silent for
        the coming `decision_ms`.  Returns (obs, reward, info); reward
        is the live-node undone fraction (attacker maximizes)."""
        if self._states is None:
            raise RuntimeError("call reset() first")
        acts = jnp.asarray(actions, jnp.int32).reshape(self.n_replicas)
        self._states = self._transition(self._states, acts)
        obs = self._observe(self._states)
        return obs, obs["undone_frac"], {"time": obs["time"]}

    @property
    def states(self):
        return self._states

"""ENRGossiping: EIP-778 node-record gossip — nodes flood versioned
capability records (StatusFloodMessage) over a P2P overlay, connect to peers
with matching capabilities, with churn (periodic capability changes, node
join/leave).

Reference semantics: protocols/ENRGossiping.java (Record message :199-217,
ETHNode connectivity scoring :221-452, init + churn tasks :160-190,
capSearch driver :454-492).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Set

from ..core import stats as SH
from ..core.params import WParameters, register_protocol
from ..core.registries import registry_network_latencies, registry_node_builders
from ..core.runners import ProgressPerTime
from ..oracle.messages import StatusFloodMessage
from ..oracle.network import Network, Protocol
from ..oracle.p2p import P2PNetwork, P2PNode

PEERS_PER_CAP = 3


def _minutes_to_ms(mins: int) -> int:
    return mins * 1000 * 60


@dataclasses.dataclass
class ENRParameters(WParameters):
    time_to_change: int = _minutes_to_ms(10000)
    cap_gossip_time: int = _minutes_to_ms(5)
    discard_time: int = 100
    time_to_leave: int = _minutes_to_ms(60)
    total_peers: int = 5
    nodes: int = 50
    changing_nodes: float = 10
    max_peers: int = 50
    number_of_different_capabilities: int = 5
    cap_per_node: int = 5
    node_builder_name: Optional[str] = None
    network_latency_name: Optional[str] = None


class Record(StatusFloodMessage):
    """Node record: seq + capability key set (ENRGossiping.java:199-217)."""

    def __init__(self, source, msg_id, size, local_delay, delay_between_peers, seq, caps):
        super().__init__(msg_id, seq, size, local_delay, delay_between_peers)
        self.source = source
        self.caps = caps


class ETHNode(P2PNode):
    __slots__ = ("capabilities", "records", "start_time", "_p")

    def __init__(self, p: "ENRGossiping", capabilities: Set[str]):
        super().__init__(p.network().rd, p.nb)
        self.capabilities = capabilities
        self.records = 0
        self.start_time = 0
        self._p = p

    def is_fully_connected(self) -> bool:
        """Score threshold + per-capability connectivity BFS
        (ENRGossiping.java:226-248)."""
        p, net = self._p, self._p.network()
        if self.score_of(self.peers) < len(self.capabilities) * PEERS_PER_CAP:
            return False
        sorted_nodes = p.select_nodes_by_cap([e for e in net.all_nodes if not e.is_down()])
        cap_keys = [k for k in sorted_nodes if k in self.capabilities]
        for key in cap_keys:
            cap_set = list(sorted_nodes[key])
            if self.is_part_of_network(cap_set):
                return False
        return True

    def added_value(self, p_node: "ETHNode") -> int:
        s1 = self.score_of(self.peers)
        added = list(self.peers)
        added.append(p_node)
        s2 = self.score_of(added)
        return s2 - s1

    def can_connect(self, p_node: "ETHNode") -> bool:
        return not p_node.is_down() and len(p_node.peers) < self._p.params.max_peers

    def start(self) -> None:
        """Lifecycle hook: schedule exit (for late joiners) and periodic
        capability broadcast (ENRGossiping.java:272-294)."""
        super().start()
        p, net = self._p, self._p.network()
        self.start_time = net.time
        if self.is_fully_connected():
            self.set_done_at(self)
        start_exit = 2**31 - 1
        if net.time > 1:
            # initial nodes never exit: keeps the simulation simpler
            start_exit = net.time + net.rd.next_int(p.params.time_to_leave)
            net.register_task(self.exit_network, start_exit, self)
        start_broadcast = net.time + net.rd.next_int(p.params.cap_gossip_time) + 1
        if start_broadcast < start_exit:
            net.register_periodic_task(
                self.broadcast_capabilities, start_broadcast, p.params.cap_gossip_time, self
            )

    def on_flood(self, from_node, flood_message) -> None:
        """Evaluate the source of an incoming record as a new peer
        (ENRGossiping.java:296-322)."""
        rc = flood_message
        if not self.can_connect(rc.source):
            return
        if rc.source in self.peers:
            return
        added_value = self.added_value(rc.source)
        if added_value == 0:
            return
        if len(self.peers) >= self._p.params.max_peers:
            if not self.remove_worse_if_possible(rc.source):
                return
        self.connect(rc.source)

    def set_done_at(self, n: "ETHNode") -> None:
        net = self._p.network()
        if n.done_at == 0 and self.is_fully_connected():
            n.done_at = max(1, net.time - n.start_time)

    def is_part_of_network(self, nodes_by_cap: List["ETHNode"]) -> bool:
        """BFS over same-capability peers; true if we reach FEWER than half
        (ENRGossiping.java:330-360)."""
        threshold = len(nodes_by_cap) // 2
        queue: Set[ETHNode] = set(n for n in nodes_by_cap if n in self.peers)
        explored: Set[ETHNode] = {self}
        while queue:
            current = next(iter(queue))
            if current is not self:
                child_nodes = [
                    n for n in nodes_by_cap if n in current.peers and n not in explored
                ]
                queue.remove(current)
                queue.update(child_nodes)
                explored.add(current)
            else:
                queue.remove(current)
        return len(explored) < threshold

    def connect(self, n: "ETHNode") -> None:
        self._p.network().create_link(self, n)
        self.set_done_at(self)
        self.set_done_at(n)

    def broadcast_capabilities(self) -> None:
        net = self._p.network()
        r = Record(self, self.node_id, 1, 10, 10, self.records, self.capabilities)
        self.records += 1
        net.send(r, self, self.peers)

    def change_cap(self) -> None:
        net = self._p.network()
        self.capabilities = self._p.generate_cap()
        r = Record(self, self.node_id, 1, 10, 10, self.records, self.capabilities)
        self.records += 1
        net.send(r, self, self.peers)

    def score_of(self, peers: List["ETHNode"]) -> int:
        """Matching-capability score, each cap counted at most PEERS_PER_CAP
        times (ENRGossiping.java:395-409)."""
        found: List[str] = []
        for n in peers:
            for s in n.capabilities:
                if s in self.capabilities:
                    found.append(s)
        score = 0
        for cap in found:
            score += min(found.count(cap), PEERS_PER_CAP)
        return score

    def remove_worse_if_possible(self, replacement: "ETHNode") -> bool:
        """(ENRGossiping.java:417-438)."""
        to_remove = replacement
        max_score = self.score_of(self.peers)
        c_p = list(self.peers)
        for i in range(len(self.peers)):
            cur = c_p[i]
            c_p[i] = replacement
            score = self.score_of(c_p)
            c_p[i] = cur
            if score > max_score:
                max_score = score
                to_remove = cur
        if to_remove is not replacement:
            self._p.network().remove_link(self, to_remove)
            return True
        return False

    def exit_network(self) -> None:
        net = self._p.network()
        live = sum(1 for n in net.all_nodes if not n.is_down())
        if live <= self._p.params.total_peers:
            raise RuntimeError(
                f"We don't have enough peers left, live={live}, "
                f"params.totalPeers={self._p.params.total_peers}"
            )
        net.disconnect(self)
        net.get_node_by_id(self.node_id).stop()


@register_protocol("ENRGossiping", ENRParameters)
class ENRGossiping(Protocol):
    def __init__(self, params: ENRParameters):
        self.params = params
        self._network: P2PNetwork[ETHNode] = P2PNetwork(params.total_peers, True)
        self.nb = registry_node_builders.get_by_name(params.node_builder_name)
        self._network.set_network_latency(
            registry_network_latencies.get_by_name(params.network_latency_name)
        )
        self.changed_nodes: List[ETHNode] = []

    def network(self) -> Network:
        return self._network

    def copy(self) -> "ENRGossiping":
        return ENRGossiping(self.params)

    def generate_cap(self) -> Set[str]:
        caps: Set[str] = set()
        while len(caps) < self.params.cap_per_node:
            cap = self._network.rd.next_int(self.params.number_of_different_capabilities)
            caps.add(f"cap_{cap}")
        return caps

    def select_nodes_by_cap(self, nodes: List[ETHNode]) -> Dict[str, List[ETHNode]]:
        m: Dict[str, List[ETHNode]] = {}
        for n in nodes:
            for cap in n.capabilities:
                m.setdefault(cap, []).append(n)
        return m

    def _select_changing_nodes(self) -> None:
        # NOTE: multiplies totalPeers (not NODES) — reference quirk
        # (ENRGossiping.java:142-148); duplicates allowed.
        changing_cap_nodes = int(self.params.total_peers * self.params.changing_nodes)
        self.changed_nodes = []
        while len(self.changed_nodes) < changing_cap_nodes:
            self.changed_nodes.append(
                self._network.get_node_by_id(self._network.rd.next_int(self.params.total_peers))
            )

    def _add_new_node(self) -> None:
        n = ETHNode(self, self.generate_cap())
        self._network.add_node(n)
        while len(n.peers) < self.params.total_peers:
            peer_id = self._network.rd.next_int(len(self._network.all_nodes))
            if not self._network.get_node_by_id(peer_id).is_down():
                self._network.create_link(n, self._network.get_node_by_id(peer_id))
        n.start()

    def init(self) -> None:
        for _ in range(self.params.nodes):
            self._network.add_node(ETHNode(self, self.generate_cap()))
        self._network.set_peers()

        self._select_changing_nodes()
        for n in self.changed_nodes:
            start = self._network.rd.next_int(self.params.time_to_change) + 1
            self._network.register_periodic_task(
                n.change_cap, start, self.params.time_to_change, n
            )
        caps: Dict[str, int] = {}
        for n in self._network.all_nodes:
            for s in n.capabilities:
                caps[s] = caps.get(s, 0) + 1
        for v in caps.values():
            if v == 1:
                raise RuntimeError("Capabilities are not well distributed")
        # Divided by 8 to aim for the expected value
        self._network.register_periodic_task(
            self._add_new_node, 0, self.params.time_to_leave // 8,
            self._network.get_node_by_id(0),
        )

    def cap_search(self, max_time_ms: int = 1000 * 60 * 60 * 10, graph_path=None, verbose=False):
        """Scenario driver (ENRGossiping.java:454-492): time for late-joining
        nodes to find their capabilities."""
        params = self.params

        class _Getter(SH.StatsGetter):
            def fields(self):
                return ["min", "max", "avg"]

            def get(self, live_nodes):
                nodes = [n for n in live_nodes if n.node_id > params.nodes and n.done_at > 1]
                if not nodes:
                    return SH.SimpleStats(0, 0, 0)
                return SH.get_stats_on(nodes, lambda n: n.done_at)

        ppt = ProgressPerTime(
            self, "", "Average time (in min) to find capabilities", _Getter(),
            1, None, 1000 * 60 * 30, verbose,
        )
        return ppt.run(lambda p1: p1.network().time <= max_time_ms, graph_path)

    def __str__(self) -> str:
        p = self.params
        return (
            f"ENRGossiping{{timeToChange={p.time_to_change}, capGossipTime={p.cap_gossip_time}, "
            f"discardTime={p.discard_time}, timeToLeave={p.time_to_leave}, "
            f"totalPeers={p.total_peers}, NODES={p.nodes}, changingNodes={p.changing_nodes}, "
            f"numberOfDifferentCapabilities={p.number_of_different_capabilities}, "
            f"numberOfCapabilityPerNode={p.cap_per_node}}}"
        )


def main():
    ENRGossiping(ENRParameters()).cap_search(verbose=True)


if __name__ == "__main__":
    main()

"""Batched Dfinity: the three-role random-beacon consensus on the batched
engine — block producers, attester committees, and beacon nodes driving a
notarized chain with 3-second rounds.

Reference semantics: protocols/Dfinity.java (comparator :107-130, messages
:132-186, BlockProducerNode :215-263, AttesterNode :265-351,
RandomBeaconNode :353-424, init :426-450), via the oracle port
`protocols/dfinity.py`.

TPU-first design:

  * the block DAG is a **preallocated block table** (SURVEY §7 step 7): a
    block's identity is its (height, producer) pair — each producer
    proposes at most once per height (BlockProducerNode.onRandomBeaconOnce
    guards on head.height == h-1 and the last_random_beacon once-guard) —
    so slot = (height-1) * n_bp + producer fixes every shape at
    `max_heights * n_bp` slots with (exists, proposal_time, parent) columns;
  * the Dfinity comparator collapses to height-with-incumbent-ties: the
    hasDirectLink branch only fires when heights differ, where it agrees
    with the height rule, and equal heights return 0 (the reference's
    producer-vs-itself quirk, Dfinity.java:128-129) — so fork choice is a
    scatter-max of (height, -slot) keys, no ancestor walks;
  * vote / beacon-exchange sets collapse to COUNTERS: every attester votes
    at most once per block and every beacon exchanges at most once per
    height (both structurally, on the sender side), so the receiver-side
    dedup sets of the reference are reachable by count alone (+ a
    self-vote / self-exchange flag);
  * all timing is message-driven (TICK_INTERVAL None): the reference's
    far-future beacon re-exchange (wt = parent.proposalTime + 2*roundTime,
    Dfinity.java:396-405) is an Emission with an explicit future
    send_time, and the engine's empty-ms jump skips the dead time.

Same-tick semantics deltas (documented engine-wide): same-ms deliveries
are simultaneous; a beacon advances at most one height per tick (the
oracle can chain two notarized blocks in one ms — unobserved in practice
since consecutive notarizations are latency-separated).
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
import numpy as np

from ..core.node import build_node_columns
from ..core.registries import registry_network_latencies
from ..engine import BatchedNetwork, BatchedProtocol, Emission
from .dfinity import (
    AttesterNode,
    BlockProducerNode,
    Dfinity,
    DfinityParameters,
    RandomBeaconNode,
)


class BatchedDfinity(BatchedProtocol):
    MSG_TYPES = ["PROPOSAL", "VOTE", "RBE", "RBR", "SEND_BLOCK"]
    PAYLOAD_WIDTH = 2  # (block slot | height, rd)
    TICK_INTERVAL = None  # pure message protocol

    def __init__(self, params: DfinityParameters, roles: dict, max_heights: int):
        self.params = params
        self.max_heights = max_heights
        self.n_att = params.attesters_count
        self.n_bp = params.block_producers_count
        self.n_bcn = params.random_beacon_count
        self.n_nodes = 1 + self.n_att + self.n_bp + self.n_bcn  # + observer
        self.max_b = max_heights * self.n_bp
        # static role columns
        self.is_att = jnp.asarray(roles["is_att"])
        self.is_bp = jnp.asarray(roles["is_bp"])
        self.is_bcn = jnp.asarray(roles["is_bcn"])
        self.my_round = jnp.asarray(roles["my_round"], jnp.int32)
        self.bp_local = jnp.asarray(roles["bp_local"], jnp.int32)  # -1 if not BP
        self.att_ids = jnp.asarray(roles["att_ids"], jnp.int32)  # [n_att]
        self.bp_ids = jnp.asarray(roles["bp_ids"], jnp.int32)
        self.bcn_ids = jnp.asarray(roles["bcn_ids"], jnp.int32)
        self.all_ids = jnp.arange(self.n_nodes, dtype=jnp.int32)

    def proto_init(self, n_nodes: int):
        n, mb, mh = self.n_nodes, self.max_b, self.max_heights
        zi = lambda s: jnp.zeros(s, jnp.int32)
        return {
            "blk_exists": jnp.zeros(mb, bool),
            "blk_time": zi(mb),
            "blk_parent": jnp.full(mb, -1, jnp.int32),
            "seen": jnp.zeros((n, mb), bool),
            "head_slot": jnp.full(n, -1, jnp.int32),  # -1 = genesis
            "cm_blk": jnp.zeros((n, mb), bool),
            "cm_h": jnp.zeros((n, mh + 2), bool),
            "last_beacon": zi(n),
            "vote_for_h": jnp.full(n, -1, jnp.int32),
            "self_voted": jnp.zeros((n, mb), bool),
            "vote_cnt": zi((n, mb)),
            "prop_buf": jnp.zeros((n, mb), bool),
            # beacon state (send_rb already pre-applied for t=0 init)
            "bcn_height": jnp.ones(n, jnp.int32),
            "bcn_last_sent": jnp.ones(n, jnp.int32),
            "exch_cnt": zi((n, mh + 2)),
            "exch_self": jnp.zeros((n, mh + 2), bool),
        }

    # -- helpers -------------------------------------------------------------
    def _slot_h(self, slot):
        return slot // self.n_bp + 1

    def _head_h(self, head_slot):
        return jnp.where(head_slot < 0, 0, self._slot_h(head_slot))

    def initial_emissions(self, net, state):
        """init (Dfinity.java:426-450): every beacon node send_rb()s the
        height-1 beacon to all nodes at t + attestation_construction_time."""
        p = self.params
        k = self.n_bcn * self.n_nodes
        frm = jnp.repeat(self.bcn_ids, self.n_nodes)
        to = jnp.tile(self.all_ids, self.n_bcn)
        return [
            Emission(
                mask=jnp.ones(k, bool),
                from_idx=frm,
                to_idx=to,
                mtype=self.mtype("RBR"),
                payload=jnp.stack(
                    [jnp.ones(k, jnp.int32), jnp.ones(k, jnp.int32)], axis=1
                ),
                send_time=jnp.full(k, p.attestation_construction_time, jnp.int32),
            )
        ]

    # -- the whole protocol runs in deliver ----------------------------------
    def deliver(self, net, state, deliver_mask):
        p = self.params
        proto = dict(state.proto)
        n, mb, mh = self.n_nodes, self.max_b, self.max_heights
        t = state.time
        ids = self.all_ids
        to, frm = state.msg_to, state.msg_from
        pay0 = jnp.clip(state.msg_payload[:, 0], 0, mb - 1)
        payh = jnp.clip(state.msg_payload[:, 0], 0, mh + 1)
        pay1 = state.msg_payload[:, 1]
        emissions = []

        is_prop = deliver_mask & (state.msg_type == self.mtype("PROPOSAL"))
        is_vote = deliver_mask & (state.msg_type == self.mtype("VOTE"))
        is_rbe = deliver_mask & (state.msg_type == self.mtype("RBE"))
        is_rbr = deliver_mask & (state.msg_type == self.mtype("RBR"))
        is_sblk = deliver_mask & (state.msg_type == self.mtype("SEND_BLOCK"))

        # ---- A. block arrivals (on_block, BlockChainNode + roles) ---------
        new_blk = jnp.zeros((n, mb), bool).at[to, pay0].max(is_sblk, mode="drop")
        new_blk = new_blk & ~proto["seen"] & proto["blk_exists"][None, :]
        proto["seen"] = proto["seen"] | new_blk

        # fork choice: height-with-incumbent-ties (comparator :107-130)
        slots = jnp.arange(mb, dtype=jnp.int32)
        h_of = self._slot_h(slots)  # [mb]
        key = jnp.where(new_blk, h_of[None, :] * (mb + 1) + (mb - slots[None, :]), -1)
        best_key = jnp.max(key, axis=1)
        best_slot = jnp.where(
            best_key >= 0, mb - (best_key % (mb + 1)), -1
        ).astype(jnp.int32)
        best_h = jnp.where(best_key >= 0, best_key // (mb + 1), 0)
        cur_h = self._head_h(proto["head_slot"])
        adopt = best_h > cur_h
        proto["head_slot"] = jnp.where(adopt, best_slot, proto["head_slot"])
        head_h = self._head_h(proto["head_slot"])

        # attester on_block (:229-236): committee sets + vote reset
        att_new = new_blk & self.is_att[:, None]
        proto["cm_blk"] = proto["cm_blk"] | att_new
        got_h = jnp.zeros((n, mh + 2), bool).at[
            jnp.repeat(ids, mb).reshape(n, mb),
            jnp.broadcast_to(h_of[None, :], (n, mb)),
        ].max(att_new, mode="drop")
        proto["cm_h"] = proto["cm_h"] | got_h
        vreset = jnp.any(
            att_new & (h_of[None, :] == proto["vote_for_h"][:, None]), axis=1
        )
        proto["vote_for_h"] = jnp.where(vreset, -1, proto["vote_for_h"])

        # beacon on_block (:387-410): height advance + exchange/send_rb
        bcn_adv = self.is_bcn & jnp.any(new_blk, axis=1) & (head_h == proto["bcn_height"])
        nh = jnp.clip(proto["bcn_height"] + 1, 0, mh + 1)
        proto["bcn_height"] = jnp.where(bcn_adv, nh, proto["bcn_height"])
        h_idx = jnp.where(bcn_adv, nh, 0)
        not_self = ~proto["exch_self"][ids, h_idx]
        add_self = bcn_adv & not_self
        proto["exch_self"] = proto["exch_self"].at[ids, h_idx].max(add_self, mode="drop")
        proto["exch_cnt"] = proto["exch_cnt"].at[ids, h_idx].add(
            add_self.astype(jnp.int32), mode="drop"
        )
        rb_now_a = add_self & (proto["exch_cnt"][ids, h_idx] >= p.majority)
        # not enough exchanges yet: schedule RandomBeaconExchange(newH) to
        # the beacon committee at wt = head.parent.proposalTime + 2*roundTime
        need_exch = bcn_adv & ~rb_now_a
        par = proto["blk_parent"][jnp.clip(proto["head_slot"], 0, mb - 1)]
        par_time = jnp.where(
            proto["head_slot"] < 0,
            0,
            jnp.where(par < 0, 0, proto["blk_time"][jnp.clip(par, 0, mb - 1)]),
        )
        wt = par_time + 2 * p.round_time
        wt = jnp.where(wt <= t, t + p.attestation_construction_time, wt)
        kbb = self.n_bcn * self.n_bcn
        emissions.append(
            Emission(
                mask=jnp.repeat(need_exch[self.bcn_ids], self.n_bcn),
                from_idx=jnp.repeat(self.bcn_ids, self.n_bcn),
                to_idx=jnp.tile(self.bcn_ids, self.n_bcn),
                mtype=self.mtype("RBE"),
                payload=jnp.stack(
                    [
                        jnp.repeat(nh[self.bcn_ids], self.n_bcn),
                        jnp.zeros(kbb, jnp.int32),
                    ],
                    axis=1,
                ),
                send_time=jnp.repeat(wt[self.bcn_ids], self.n_bcn),
            )
        )

        # ---- B. beacon results (on_random_beacon, :133-140) ---------------
        rbr_h = jnp.zeros(n, jnp.int32).at[to].max(
            jnp.where(is_rbr, payh, 0), mode="drop"
        )
        trig = rbr_h > proto["last_beacon"]
        # rd == height for every beacon (send_rb :274-279), so rd = rbr_h
        rd = rbr_h
        proto["last_beacon"] = jnp.where(trig, rbr_h, proto["last_beacon"])

        # BP: propose when selected and the parent is in hand (:177-181)
        bp_sel = (
            trig
            & self.is_bp
            & (rd % p.block_producers_round == self.my_round)
            & (head_h == rbr_h - 1)
            & (rbr_h <= mh)
        )
        new_slot = jnp.clip((rbr_h - 1) * self.n_bp + self.bp_local, 0, mb - 1)
        w_slot = jnp.where(bp_sel, new_slot, mb)
        proto["blk_exists"] = proto["blk_exists"].at[w_slot].set(True, mode="drop")
        proto["blk_time"] = proto["blk_time"].at[w_slot].set(t, mode="drop")
        proto["blk_parent"] = proto["blk_parent"].at[w_slot].set(
            proto["head_slot"], mode="drop"
        )
        kpa = self.n_bp * self.n_att
        emissions.append(
            Emission(
                mask=jnp.repeat(bp_sel[self.bp_ids], self.n_att),
                from_idx=jnp.repeat(self.bp_ids, self.n_att),
                to_idx=jnp.tile(self.att_ids, self.n_bp),
                mtype=self.mtype("PROPOSAL"),
                payload=jnp.stack(
                    [
                        jnp.repeat(new_slot[self.bp_ids], self.n_att),
                        jnp.zeros(kpa, jnp.int32),
                    ],
                    axis=1,
                ),
                send_time=jnp.full(kpa, 1, jnp.int32) * (t + p.block_construction_time),
            )
        )

        # attester committee selection (:238-253)
        att_sel = (
            trig
            & self.is_att
            & (rd % p.attesters_round == self.my_round)
            & ~proto["cm_h"][ids, jnp.clip(rbr_h, 0, mh + 1)]
        )
        proto["vote_for_h"] = jnp.where(att_sel, rbr_h, proto["vote_for_h"])

        # beacon: adopt a beacon someone else finished (:308-313)
        bcn_fwd = trig & self.is_bcn & (rbr_h > proto["bcn_height"])
        proto["bcn_last_sent"] = jnp.where(
            bcn_fwd, proto["bcn_height"], proto["bcn_last_sent"]
        )
        proto["bcn_height"] = jnp.where(bcn_fwd, rbr_h, proto["bcn_height"])

        # ---- C+D. proposals (arrived + unbuffered) and votes --------------
        prop_ev = jnp.zeros((n, mb), bool).at[to, pay0].max(is_prop, mode="drop")
        # onRandomBeaconOnce replays buffered proposals at the new height
        # then clears the buffer (:243-253)
        at_vh = h_of[None, :] == proto["vote_for_h"][:, None]
        prop_ev = prop_ev | (att_sel[:, None] & proto["prop_buf"] & at_vh)
        proto["prop_buf"] = jnp.where(att_sel[:, None], False, proto["prop_buf"])

        votable = self.is_att[:, None] & at_vh
        do_vote = prop_ev & votable & ~proto["self_voted"]
        proto["self_voted"] = proto["self_voted"] | do_vote
        # buffer future proposals (:225-227)
        buf = prop_ev & self.is_att[:, None] & ~votable & (
            h_of[None, :] > self._head_h(proto["head_slot"])[:, None]
        )
        proto["prop_buf"] = proto["prop_buf"] | buf

        # the broadcast includes the sender (send_all semantics); the oracle
        # drops the self copy via its voter set ('voter not in voters',
        # :197-199) — here the self vote is already counted by do_vote
        vote_ev = jnp.zeros((n, mb), jnp.int32).at[to, pay0].add(
            (is_vote & (frm != to)).astype(jnp.int32), mode="drop"
        )
        vote_ev = jnp.where(votable, vote_ev, 0)  # on_vote height guard (:194-200)
        proto["vote_cnt"] = proto["vote_cnt"] + vote_ev + do_vote.astype(jnp.int32)

        # majority crossings -> notarize ONE block per attester (:202-206)
        crossing = votable & (proto["vote_cnt"] >= p.majority) & (
            do_vote | (vote_ev > 0)
        )
        cross_key = jnp.where(crossing, mb - slots[None, :], 0)
        cw = jnp.argmax(cross_key, axis=1).astype(jnp.int32)
        has_cross = jnp.max(cross_key, axis=1) > 0
        proto["cm_blk"] = proto["cm_blk"].at[ids, cw].max(has_cross, mode="drop")
        proto["cm_h"] = proto["cm_h"].at[
            ids, jnp.clip(self._slot_h(cw), 0, mh + 1)
        ].max(has_cross, mode="drop")
        proto["vote_for_h"] = jnp.where(has_cross, -1, proto["vote_for_h"])
        knn = self.n_att * self.n_nodes
        emissions.append(
            Emission(
                mask=jnp.repeat(has_cross[self.att_ids], self.n_nodes),
                from_idx=jnp.repeat(self.att_ids, self.n_nodes),
                to_idx=jnp.tile(self.all_ids, self.n_att),
                mtype=self.mtype("SEND_BLOCK"),
                payload=jnp.stack(
                    [
                        jnp.repeat(cw[self.att_ids], self.n_nodes),
                        jnp.zeros(knn, jnp.int32),
                    ],
                    axis=1,
                ),
            )
        )

        # non-crossing self-votes broadcast Vote to the committee (:216-224);
        # once an attester notarizes, its remaining same-tick votes are
        # dropped (the oracle's sequential processing stops at _send_block's
        # voteForHeight reset)
        vote_out = do_vote & ~has_cross[:, None]
        for j in range(self.n_bp):
            # at most one votable height per attester -> n_bp candidate slots
            vh = jnp.clip(proto["vote_for_h"], 1, mh)
            sl = jnp.clip((vh - 1) * self.n_bp + j, 0, mb - 1)
            m = vote_out[ids, sl] & self.is_att
            kaa = self.n_att * self.n_att
            emissions.append(
                Emission(
                    mask=jnp.repeat(m[self.att_ids], self.n_att),
                    from_idx=jnp.repeat(self.att_ids, self.n_att),
                    to_idx=jnp.tile(self.att_ids, self.n_att),
                    mtype=self.mtype("VOTE"),
                    payload=jnp.stack(
                        [
                            jnp.repeat(sl[self.att_ids], self.n_att),
                            jnp.zeros(kaa, jnp.int32),
                        ],
                        axis=1,
                    ),
                    send_time=jnp.full(
                        kaa, 1, jnp.int32
                    ) * (t + p.attestation_construction_time),
                )
            )

        # ---- E. beacon exchanges (:266-272) -------------------------------
        # self copy dropped: the sender added itself at height advance
        # (exchanged set dedup, Dfinity.java:268-271)
        rbe_ok = (
            is_rbe
            & (frm != to)
            & self.is_bcn[to]
            & (payh >= proto["bcn_height"][to])
            & (payh > proto["bcn_last_sent"][to])
        )
        proto["exch_cnt"] = proto["exch_cnt"].at[to, payh].add(
            rbe_ok.astype(jnp.int32), mode="drop"
        )
        rb_now_b = (
            self.is_bcn
            & (
                proto["exch_cnt"][ids, jnp.clip(proto["bcn_height"], 0, mh + 1)]
                >= p.majority
            )
            & (proto["bcn_height"] > proto["bcn_last_sent"])
            & (
                jnp.zeros(n, bool).at[to].max(rbe_ok, mode="drop")
                | rb_now_a
            )
        )
        proto["bcn_last_sent"] = jnp.where(
            rb_now_b, proto["bcn_height"], proto["bcn_last_sent"]
        )
        kbn = self.n_bcn * self.n_nodes
        emissions.append(
            Emission(
                mask=jnp.repeat(rb_now_b[self.bcn_ids], self.n_nodes),
                from_idx=jnp.repeat(self.bcn_ids, self.n_nodes),
                to_idx=jnp.tile(self.all_ids, self.n_bcn),
                mtype=self.mtype("RBR"),
                payload=jnp.stack(
                    [
                        jnp.repeat(proto["bcn_height"][self.bcn_ids], self.n_nodes),
                        jnp.repeat(proto["bcn_height"][self.bcn_ids], self.n_nodes),
                    ],
                    axis=1,
                ),
                send_time=jnp.full(
                    kbn, 1, jnp.int32
                ) * (t + p.attestation_construction_time),
            )
        )

        return state._replace(proto=proto), emissions

    def all_done(self, state):
        return jnp.asarray(False)  # Dfinity runs open-ended, like the oracle

    def head_height(self, state):
        """Per-node head height (the print_stat observable)."""
        return self._head_h(state.proto["head_slot"])


def make_dfinity(
    params: Optional[DfinityParameters] = None,
    max_heights: int = 64,
    capacity: int = 1 << 13,
    seed: int = 0,
    latency_name: Optional[str] = None,
):
    """Host-side construction: the oracle builds the node population (same
    RNG stream — observer, attesters, producers, beacons in id order)."""
    params = params or DfinityParameters()
    oracle = Dfinity(params)
    oracle.init()
    net_o = oracle.network()
    nodes = net_o.all_nodes
    n = len(nodes)

    roles = {
        "is_att": np.array([isinstance(nd, AttesterNode) for nd in nodes]),
        "is_bp": np.array([isinstance(nd, BlockProducerNode) for nd in nodes]),
        "is_bcn": np.array([isinstance(nd, RandomBeaconNode) for nd in nodes]),
        "my_round": np.array(
            [getattr(nd, "my_round", 0) for nd in nodes], dtype=np.int32
        ),
        "bp_local": np.full(n, -1, dtype=np.int32),
        "att_ids": np.array(
            [nd.node_id for nd in nodes if isinstance(nd, AttesterNode)],
            dtype=np.int32,
        ),
        "bp_ids": np.array(
            [nd.node_id for nd in nodes if isinstance(nd, BlockProducerNode)],
            dtype=np.int32,
        ),
        "bcn_ids": np.array(
            [nd.node_id for nd in nodes if isinstance(nd, RandomBeaconNode)],
            dtype=np.int32,
        ),
    }
    for j, nid in enumerate(roles["bp_ids"]):
        roles["bp_local"][nid] = j

    # the reference never applies networkLatencyName (Dfinity.java:86-90);
    # callers pick the model explicitly, like DfinityTest does
    latency = registry_network_latencies.get_by_name(latency_name)
    city_index = getattr(latency, "city_index", None)
    cols = build_node_columns(nodes, city_index)
    proto = BatchedDfinity(params, roles, max_heights)
    net = BatchedNetwork(proto, latency, n, capacity=capacity)
    state = net.init_state(cols, seed=seed, proto=proto.proto_init(n))
    return net, state

"""Ethereum proof-of-work family: miners with Bernoulli-per-10ms mining,
EIP-standard difficulty adjustment, uncles/rewards, selfish-mining attacks
(Eyal-Sirer), and a stepwise RL-agent miner.

Reference semantics: protocols/ethpow/ETHPoW.java (POWBlock difficulty
:284-296, rewards :182-257, uncle check :260-270), ETHMiner.java (mining
loop :118-148, uncle selection :66-115, strategy hooks :25-51),
ETHSelfishMiner.java / ETHSelfishMiner2.java (algorithm 1 of the
selfish-mining paper), ETHMinerAgent.java (stepwise goNextStep bridge —
callable directly from Python here, no pyjnius needed), ETHAgentMiner.java
(decision CSV logger).
"""

from __future__ import annotations

import dataclasses
import functools
import math
from typing import Dict, List, Optional, Set

from ..core.node import NodeBuilder
from ..core.params import WParameters, register_protocol
from ..core.registries import registry_network_latencies, registry_node_builders
from ..oracle.blockchain import Block, BlockChainNetwork, BlockChainNode, SendBlock
from ..oracle.network import Protocol


@dataclasses.dataclass
class ETHPoWParameters(WParameters):
    node_builder_name: Optional[str] = None
    network_latency_name: Optional[str] = None
    number_of_miners: int = 1
    byz_class_name: Optional[str] = None
    byz_mining_ratio: float = 0

    def __post_init__(self):
        if not self.byz_class_name:
            self.byz_class_name = None
            self.byz_mining_ratio = 0


class Reward:
    __slots__ = ("who", "amount")

    def __init__(self, who: "ETHMiner", amount: float):
        self.who = who
        self.amount = amount

    @staticmethod
    def sum_rewards(sum_: Dict["ETHMiner", float], rewards: List["Reward"]) -> None:
        for r in rewards:
            sum_[r.who] = sum_.get(r.who, 0.0) + r.amount


class POWBlock(Block):
    """Block with Constantinople difficulty and uncle rewards
    (ETHPoW.java:118-297)."""

    __slots__ = ("difficulty", "total_difficulty", "transactions", "uncles")

    def __init__(
        self,
        producer: Optional["ETHMiner"],
        father: Optional["POWBlock"],
        time: int,
        uncles: Optional[Set["POWBlock"]] = None,
        height: Optional[int] = None,
        diff: Optional[int] = None,
        genesis: bool = False,
    ):
        self.uncles: List[POWBlock] = []
        self.transactions: List = []
        if genesis:
            # starts at mainnet block 7951081 (ETHPoW.java:158-164)
            super().__init__(height=7951081, genesis=True)
            self.difficulty = 1949482043446410
            self.total_difficulty = 10591882213905570860929
            return
        if diff is not None:
            # test constructor (ETHPoW.java:167-175)
            super().__init__(producer, height, father, True, time)
            self.difficulty = diff
            self.total_difficulty = (
                father.total_difficulty + diff if father is not None else diff
            )
            return
        super().__init__(producer, father.height + 1, father, True, time)
        if uncles:
            if len(uncles) > 2:
                raise ValueError(f"Can't have more than 2 uncles: {self}, {len(uncles)}")
            for u in uncles:
                if not self.is_possible_uncle(u):
                    raise ValueError(f"{u} can't be an uncle of {self}")
                self.uncles.append(u)
        self.difficulty = self.calculate_difficulty(father, time)
        self.total_difficulty = father.total_difficulty + self.difficulty

    def on_calculate_difficulty(self, all_: int, father, diff: int, bomb: int) -> int:
        return all_

    def rewards(self) -> List[Reward]:
        """Block + uncle rewards (ETHPoW.java:182-197)."""
        rwd = 2.0
        if not self.uncles:
            return [Reward(self.producer, rwd)]
        res = []
        p_r = rwd
        for u in self.uncles:
            u_r = (rwd * (u.height + 8 - self.height)) / 8
            p_r += rwd / 32
            res.append(Reward(u.producer, u_r))
        res.append(Reward(self.producer, p_r))
        return res

    def all_rewards(self, until_height: int = 0) -> Dict["ETHMiner", float]:
        res: Dict[ETHMiner, float] = {}
        cur = self
        while cur.producer is not None and cur.height >= until_height - 1:
            Reward.sum_rewards(res, cur.rewards())
            cur = cur.parent
        return res

    def all_rewards_by_id(self, sum_: Dict[int, float], until_height: int) -> None:
        cur = self
        while cur.producer is not None and cur.height > until_height:
            for r in cur.rewards():
                sum_[r.who.node_id] = sum_.get(r.who.node_id, 0.0) + r.amount
            cur = cur.parent

    def avg_difficulty(self, until_height: int) -> int:
        cur = self
        while cur.producer is not None and cur.height > until_height:
            cur = cur.parent
        if cur is self:
            return cur.difficulty
        diff = self.total_difficulty - cur.total_difficulty + cur.difficulty
        blocks = 1 + self.height - cur.height
        return diff // blocks

    def uncle_rate(self, until_height: int) -> float:
        uncles = 0.0
        cur = self
        first = None
        while cur.producer is not None and cur.height > until_height:
            uncles += len(cur.uncles)
            first = cur
            cur = cur.parent
        return 0.0 if first is None else uncles / (uncles + self.height - first.height)

    def is_possible_uncle(self, b: "POWBlock") -> bool:
        """(ETHPoW.java:260-270)."""
        if b.height >= self.height or self.height - b.height > 7:
            return False
        cur = self
        while cur is not None and cur.height > b.height:
            cur = cur.parent
        return cur is not None and cur.parent is b.parent

    @staticmethod
    def create_genesis() -> "POWBlock":
        return POWBlock(None, None, 0, genesis=True)

    def calculate_difficulty(self, father: "POWBlock", ts: int) -> int:
        """Constantinople difficulty incl. the EIP-100/EIP-1234 bomb
        (ETHPoW.java:284-296); all divisions are Java long divisions on
        positive operands."""
        gap = (ts - father.proposal_time) // 9000
        y = 1 if not father.uncles else 2
        ugap = max(-99, y - gap)
        diff = (father.difficulty // 2048) * ugap
        periods = (father.height - 4_999_999) // 100_000
        bomb = 2 ** (periods - 2) if periods > 1 else diff
        all_ = father.difficulty + diff + bomb
        return self.on_calculate_difficulty(all_, father, diff, bomb)


def pow_block_cmp(o1: POWBlock, o2: POWBlock) -> int:
    """(ETHPoW.java:299-310)."""
    if o1 is o2:
        return 0
    if not o2.valid:
        return 1
    if not o1.valid:
        return -1
    return (o1.total_difficulty > o2.total_difficulty) - (
        o1.total_difficulty < o2.total_difficulty
    )


class ETHPoWNode(BlockChainNode):
    __slots__ = ("_network",)

    def __init__(self, network: BlockChainNetwork, nb: NodeBuilder, genesis: POWBlock):
        super().__init__(network.rd, nb, False, genesis)
        self._network = network

    def best(self, cur: POWBlock, alt: POWBlock) -> POWBlock:
        """Fork choice by total difficulty; prefer own block on ties
        (ETHPoW.java:337-348)."""
        if alt is None:
            return cur
        if cur is None:
            return alt
        res = pow_block_cmp(cur, alt)
        if res == 0:
            return alt if alt.producer is self else cur
        return cur if res > 0 else alt


class ETHMiner(ETHPoWNode):
    """Honest miner with strategy hooks (ETHMiner.java)."""

    __slots__ = ("hash_power_ghs", "in_mining", "mined_to_send", "threshold")

    def __init__(self, network, nb, hash_power_ghs: int, genesis: POWBlock):
        super().__init__(network, nb, genesis)
        self.hash_power_ghs = hash_power_ghs
        self.in_mining: Optional[POWBlock] = None
        self.mined_to_send: Set[POWBlock] = set()
        self.threshold = 0.0

    # -- strategy hooks (ETHMiner.java:25-51) ------------------------------
    def include_uncle(self, uncle: POWBlock) -> bool:
        return True

    def send_mined_block(self, mined: POWBlock) -> bool:
        return True

    def extra_send_delay(self, mined: POWBlock) -> int:
        return 0

    def switch_mining(self, rcv: POWBlock) -> bool:
        return True

    def on_new_head(self, old_head: POWBlock, new_head: POWBlock) -> None:
        pass

    def on_mined_block(self, mined: POWBlock) -> None:
        pass

    def on_received_block(self, rcv: POWBlock) -> None:
        pass

    def close(self) -> None:
        pass

    # ----------------------------------------------------------------------
    def depth(self, b: Optional[POWBlock]) -> int:
        """Blocks we mined in a row from 'b' (ETHMiner.java:54-63)."""
        res = 0
        while b is not None and b.producer is self:
            res += 1
            b = b.parent
        return res

    def possible_uncles(self, father: POWBlock) -> List[POWBlock]:
        """(ETHMiner.java:66-90)."""
        res: List[POWBlock] = []
        included: Set[POWBlock] = set()
        b = father
        h = 0
        while b is not None and h < 8:
            included.add(b)
            included.update(b.uncles)
            b = b.parent
            h += 1
        for h in range(father.height, father.height - 7, -1):
            # block-id order: the reference iterates a HashSet (arbitrary
            # but fixed per JVM run); iterating by id keeps our runs
            # seed-reproducible, which the Java version doesn't guarantee
            rcv = sorted(self.blocks_received_by_height.get(h, set()), key=lambda b: b.id)
            for u in rcv:
                if (
                    u not in included
                    and (u.parent is father.parent or father.is_possible_uncle(u))
                    and self.include_uncle(u)
                ):
                    res.append(u)
        res.sort(key=functools.cmp_to_key(self._uncle_cmp))
        return res

    def _uncle_cmp(self, o1: POWBlock, o2: POWBlock) -> int:
        """Own uncles first (higher height first among ours); otherwise
        smallest height first (ETHMiner.java:98-115)."""
        if o1.producer is self:
            if o2.producer is not o1.producer:
                return -1
            return (o2.height > o1.height) - (o2.height < o1.height)
        if o2.producer is self:
            return 1
        return (o1.height > o2.height) - (o1.height < o2.height)

    def mine10ms(self) -> bool:
        """One Bernoulli trial per 10 ms period (ETHMiner.java:118-129)."""
        if self.in_mining is None:
            self.start_new_mining(self.head)
        assert self.in_mining is not None
        if self._network.rd.next_double() < self.threshold:
            self._on_found_new_block(self.in_mining)
            return True
        return False

    def start_new_mining(self, father: POWBlock) -> None:
        us = self.possible_uncles(father)
        uss = set(us[:2]) if us else set()
        self.in_mining = POWBlock(self, father, self._network.time, uss)
        self.threshold = self.solve_in_10ms(self.in_mining.difficulty)

    def lucky_mine(self) -> None:
        """Tests: force a successful mining (ETHMiner.java:143-148)."""
        if not self.mine10ms():
            self.threshold = 10
            self.mine10ms()

    def send_block(self, mined: POWBlock) -> None:
        if mined.producer is not self:
            raise ValueError(f"logic error: you're not the producer of this block{mined}")
        send_time = self._network.time + 1 + self.extra_send_delay(mined)
        if send_time < 1:
            raise ValueError(f"extraSendDelay({mined}) sent a negative time")
        self._network.send_all(SendBlock(mined), self, send_time)
        self.mined_to_send.discard(mined)

    def send_all_mined(self) -> None:
        # NOTE: invokes the boolean *hook* send_mined_block (not send_block),
        # exactly like the reference (ETHMiner.java:165-171) — for miners
        # whose hook returns False (selfish/agent) the withheld blocks are
        # dropped, not broadcast.  Kept verbatim: it is the reference's
        # observable behavior, quirky as it is.
        all_ = list(self.mined_to_send)
        self.mined_to_send.clear()
        for b in all_:
            self.send_mined_block(b)

    def _on_found_new_block(self, mined: POWBlock) -> None:
        old_head = self.head
        self.in_mining = None
        if self.send_mined_block(mined):
            self.send_block(mined)
        else:
            self.mined_to_send.add(mined)
        if not BlockChainNode.on_block(self, mined):
            raise RuntimeError(f"invalid mined block:{mined}")
        if mined is self.head:
            self.on_new_head(old_head, mined)
        self.on_mined_block(mined)

    def get_mined_to_send(self) -> int:
        return len(self.mined_to_send)

    def on_block(self, b: POWBlock) -> bool:
        """(ETHMiner.java:197-222)."""
        old_head = self.head
        if not super().on_block(b):
            return False
        if b is self.head:
            self.on_new_head(old_head, b)
            # someone sent us a new head: switch our mining to it
            if self.switch_mining(b):
                self.in_mining = None
        elif self.in_mining is not None:
            # maybe 'b' is an uncle candidate for the block we're mining
            if self.in_mining.is_possible_uncle(b):
                if self.switch_mining(b):
                    self.in_mining = None
        self.on_received_block(b)
        return True

    def solve_in_10ms(self, difficulty: int) -> float:
        """P(find a hash in 10 ms) for this hash power (ETHMiner.java:225-231)."""
        hp_t_ms = (self.hash_power_ghs * 1024.0 * 1024 * 1024) / 100.0
        single = 1.0 / difficulty
        no_success = math.pow(1.0 - single, hp_t_ms)
        return 1 - no_success


class ETHSelfishMiner(ETHMiner):
    """Eyal-Sirer selfish mining, algorithm 1 (ETHSelfishMiner.java)."""

    __slots__ = ("private_miner_block", "other_miners_head")

    def __init__(self, network, nb, hash_power: int, genesis: POWBlock):
        super().__init__(network, nb, hash_power, genesis)
        self.private_miner_block: Optional[POWBlock] = None
        self.other_miners_head = genesis

    def _private_height(self) -> int:
        return 0 if self.private_miner_block is None else self.private_miner_block.height

    def send_mined_block(self, mined: POWBlock) -> bool:
        return False

    def include_uncle(self, uncle: POWBlock) -> bool:
        return True

    def on_mined_block(self, mined: POWBlock) -> None:
        if self.private_miner_block is not None and mined.height <= self.private_miner_block.height:
            raise RuntimeError(
                f"privateMinerBlock={self.private_miner_block}, mined={mined}"
            )
        self.private_miner_block = mined
        delta_p = self._private_height() - (self.other_miners_head.height - 1)
        if delta_p == 0 and self.depth(self.private_miner_block) == 2:
            self.other_miners_head = self.best(self.other_miners_head, self.private_miner_block)
            self.send_all_mined()
        self.start_new_mining(self.private_miner_block)

    def on_received_block(self, rcv: POWBlock) -> None:
        """(ETHSelfishMiner.java:56-115)."""
        self.other_miners_head = self.best(self.other_miners_head, rcv)
        if self.other_miners_head is not rcv:
            return
        delta_p = self._private_height() - (self.other_miners_head.height - 1)
        if delta_p <= 0:
            # they won: we move to their chain
            self.send_all_mined()
            self.start_new_mining(self.head)
        else:
            if delta_p == 1 or delta_p == 2:
                to_send = self.private_miner_block
            else:
                # far ahead: try to win by sending a competing block
                to_send = self.private_miner_block
                while to_send.parent in self.mined_to_send and to_send.height > rcv.height:
                    to_send = to_send.parent
                    assert to_send is not None
                if to_send.height != rcv.height:
                    f = to_send
                    while f.height != rcv.height:
                        f = f.parent
                    if f.total_difficulty < rcv.total_difficulty:
                        return
            while (
                to_send is not None
                and to_send.producer is self
                and to_send in self.mined_to_send
            ):
                self.other_miners_head = self.best(self.other_miners_head, to_send)
                self.send_block(to_send)
                to_send = to_send.parent


class ETHSelfishMiner2(ETHMiner):
    """Selfish-mining variant keyed on total difficulty (ETHSelfishMiner2.java)."""

    __slots__ = ("private_miner_block", "other_miners_head")

    def __init__(self, network, nb, hash_power: int, genesis: POWBlock):
        super().__init__(network, nb, hash_power, genesis)
        self.private_miner_block: Optional[POWBlock] = None
        self.other_miners_head = genesis

    def _private_height(self) -> int:
        return 0 if self.private_miner_block is None else self.private_miner_block.height

    def send_mined_block(self, mined: POWBlock) -> bool:
        return False

    def include_uncle(self, uncle: POWBlock) -> bool:
        return True

    def on_mined_block(self, mined: POWBlock) -> None:
        if self.private_miner_block is not None and mined.height <= self.private_miner_block.height:
            raise RuntimeError(
                f"privateMinerBlock={self.private_miner_block}, mined={mined}"
            )
        self.private_miner_block = mined
        delta_p = self._private_height() - (self.other_miners_head.height - 1)
        if delta_p == 0 and self.depth(self.private_miner_block) == 2:
            self.other_miners_head = self.best(self.other_miners_head, self.private_miner_block)
            self.send_all_mined()
        self.start_new_mining(self.private_miner_block)

    def on_received_block(self, rcv: POWBlock) -> None:
        """(ETHSelfishMiner2.java:55-81)."""
        self.other_miners_head = self.best(self.other_miners_head, rcv)
        if self.other_miners_head is not rcv:
            return
        if self.head is rcv:
            self.send_all_mined()
            self.start_new_mining(self.head)
        else:
            to_send = self.private_miner_block
            while (
                to_send.parent is not None
                and to_send.height >= rcv.height
                and to_send.parent.total_difficulty > rcv.total_difficulty
            ):
                to_send = to_send.parent
            while (
                to_send is not None
                and to_send.producer is self
                and to_send in self.mined_to_send
            ):
                self.other_miners_head = self.best(self.other_miners_head, to_send)
                self.send_block(to_send)
                to_send = to_send.parent


ON_MINED_BLOCK = 1
ON_OTHER_NEW_HEAD = 2
ON_OTHER_PRIVATE_HEAD = 3


class ETHMinerAgent(ETHMiner):
    """Stepwise miner for RL agents: `go_next_step()` runs the simulation
    until a decision is needed (ETHMinerAgent.java:38-225).  The reference
    embeds the JVM via pyjnius; here the same API is plain Python."""

    __slots__ = ("private_miner_block", "other_miners_head", "decision_needed")

    def __init__(self, network, nb, hash_power_ghs: int, genesis: POWBlock):
        super().__init__(network, nb, hash_power_ghs, genesis)
        self.private_miner_block: Optional[POWBlock] = None
        self.other_miners_head = genesis
        self.decision_needed = 0

    def send_mined_block(self, mined: POWBlock) -> bool:
        return False

    def send_mined_blocks(self, how_many: int) -> None:
        """(ETHMinerAgent.java:68-88).  The Java loop is
        `while (howMany-- > 0 && !minedToSend.isEmpty())`: the
        post-decrement leaves howMany at -1 after a fully-honored k (and
        after k=0), so the `howMany == 0` restart below fires ONLY when k
        exceeded the available withheld blocks by exactly one (including
        k=1 against an empty set) — never on k=0 and never on a
        fully-honored release.  Kept bit-exact here and mirrored by the
        batched path (ethpow_batched.agent_apply_action)."""
        if self.decision_needed == 0:
            print(
                f"no action needed: howMany={how_many}, advance={self.get_advance()}, "
                f"secretAdvance={self.get_secret_advance()}"
            )
        while True:
            how_many -= 1
            if how_many < 0 or not self.mined_to_send:
                break
            self.action_send_oldest_block_mined()
        if how_many == 0 and self.in_mining is not None and self.private_miner_block is not None:
            self.start_new_mining(self.head)
        if not self.mined_to_send:
            self.private_miner_block = None

    def go_next_step(self) -> int:
        """Run the network until the agent needs to decide
        (ETHMinerAgent.java:90-100)."""
        self.decision_needed = 0
        while self.decision_needed == 0:
            self._network.run_ms(1)
            if self.decision_needed > ON_MINED_BLOCK and not self.mined_to_send:
                self.decision_needed = 0
        return self.decision_needed

    def get_secret_advance(self) -> int:
        priv = 0 if self.private_miner_block is None else self.private_miner_block.height
        return max(priv - self.other_miners_head.height, 0)

    def get_advance(self) -> int:
        cur = self.head
        score = 0
        while cur.producer is self:
            cur = cur.parent
            score += 1
        return score

    def get_lag(self) -> int:
        cur = self.head
        score = 0
        while cur.producer is not self:
            cur = cur.parent
            score += 1
        return score

    def get_reward(self, last_blocks_count: Optional[int] = None) -> float:
        if last_blocks_count is None:
            return self.head.all_rewards().get(self, 0.0)
        return self.head.all_rewards(self.head.height - last_blocks_count).get(self, 0.0)

    def get_reward_ratio(self) -> float:
        ar = self.head.all_rewards()
        all_ = sum(ar.values())
        me = ar.get(self, 0.0)
        return me / all_ if me > 0 else 0

    def i_am_ahead(self) -> bool:
        return self.head.producer is self

    def count_my_blocks(self) -> int:
        count = 0
        cur = self.head
        while cur is not None:
            if cur.producer is self:
                count += 1
            cur = cur.parent
        return count

    def on_new_head(self, old_head: POWBlock, new_head: POWBlock) -> None:
        self.start_new_mining(new_head)

    def on_received_block(self, rcv: POWBlock) -> None:
        """(ETHMinerAgent.java:187-204)."""
        self.other_miners_head = self.best(self.other_miners_head, rcv)
        if self.head is rcv:
            self.decision_needed = ON_OTHER_NEW_HEAD
        elif self.other_miners_head is rcv:
            self.decision_needed = ON_OTHER_PRIVATE_HEAD
        cont = True
        while cont and self.mined_to_send:
            youngest = min(self.mined_to_send, key=lambda o: o.height)
            if youngest.height <= self.other_miners_head.height:
                self.send_mined_blocks(1)
            else:
                cont = False

    def on_mined_block(self, mined: POWBlock) -> None:
        self.decision_needed = ON_MINED_BLOCK
        if self.private_miner_block is not None and mined.height <= self.private_miner_block.height:
            raise RuntimeError(
                f"privateMinerBlock={self.private_miner_block}, mined={mined}"
            )
        self.private_miner_block = mined

    def action_send_oldest_block_mined(self) -> None:
        oldest = min(self.mined_to_send, key=lambda o: o.proposal_time)
        if oldest.height > self.other_miners_head.height:
            self.other_miners_head = oldest
        self.send_block(oldest)


class Decision:
    """Base for agent decisions evaluated later (ETHPoW.java:352-374)."""

    def __init__(self, taken_at_height: int, reward_at_height: int):
        self.taken_at_height = taken_at_height
        self.reward_at_height = reward_at_height

    def for_csv(self) -> str:
        raise NotImplementedError

    def __str__(self) -> str:
        return self.for_csv()

    def reward(self, current_head: POWBlock, miner: "ETHAgentMiner") -> float:
        return current_head.all_rewards(self.taken_at_height).get(miner, 0.0)


class ETHAgentMiner(ETHMiner):
    """Miner that logs decisions + delayed rewards to a CSV
    (ETHAgentMiner.java)."""

    DATA_FILE = "decisions.csv"

    __slots__ = ("decisions", "_decision_output")

    def __init__(self, network, nb, hash_power: int, genesis: POWBlock):
        super().__init__(network, nb, hash_power, genesis)
        self.decisions: List[Decision] = []
        self._decision_output = open(self.DATA_FILE, "a")

    def add_decision(self, d: Decision) -> None:
        """Insert keeping the list sorted by rewardAtHeight
        (ETHAgentMiner.java:36-53)."""
        if d.reward_at_height <= self.head.height:
            raise ValueError(f"Can't calculate a reward for {d}, head={self.head}")
        if not self.decisions or self.decisions[-1].reward_at_height <= d.reward_at_height:
            self.decisions.append(d)
        else:
            i = len(self.decisions)
            while i > 0 and self.decisions[i - 1].reward_at_height > d.reward_at_height:
                i -= 1
            self.decisions.insert(i, d)

    def on_new_head(self, old_head: POWBlock, new_head: POWBlock) -> None:
        while self.decisions and self.decisions[0].reward_at_height <= new_head.height:
            cur = self.decisions.pop(0)
            reward = cur.reward(new_head, self)
            self._decision_output.write(f"{cur.for_csv()},{reward}\n")

    def close(self) -> None:
        self._decision_output.close()


# Explicit class map replacing the reference's reflection lookup
# (ETHPoW.java:78-87); keyed by simple name, Java FQNs also accepted.
BYZ_MINER_CLASSES = {
    "ETHMiner": ETHMiner,
    "ETHSelfishMiner": ETHSelfishMiner,
    "ETHSelfishMiner2": ETHSelfishMiner2,
    "ETHMinerAgent": ETHMinerAgent,
    "ETHAgentMiner": ETHAgentMiner,
}


def resolve_miner_class(name) -> type:
    if isinstance(name, type):
        return name
    key = name.rsplit(".", 1)[-1]
    cls = BYZ_MINER_CLASSES.get(key)
    if cls is None:
        raise ValueError(f"unknown miner class {name!r}")
    return cls


@register_protocol("ETHPoW", ETHPoWParameters)
class ETHPoW(Protocol):
    def __init__(self, params: ETHPoWParameters):
        self.params = params
        self._network: BlockChainNetwork = BlockChainNetwork()
        self.nb = registry_node_builders.get_by_name(params.node_builder_name)
        self._network.set_network_latency(
            registry_network_latencies.get_by_name(params.network_latency_name)
        )
        self.genesis = POWBlock.create_genesis()

    def network(self) -> BlockChainNetwork:
        return self._network

    def copy(self) -> "ETHPoW":
        return ETHPoW(self.params)

    def get_byzantine_node(self) -> ETHMiner:
        if self.params.byz_class_name is None:
            raise ValueError("no byzantine node in this network")
        return self._network.get_node_by_id(1)  # bad node is always at pos 1

    def init(self) -> None:
        """(ETHPoW.java:70-98)."""
        p = self.params
        total_hash_power = 200 * 1024
        byz_hash_power = int(total_hash_power * p.byz_mining_ratio)
        honest_miners = p.number_of_miners if byz_hash_power == 0 else p.number_of_miners - 1
        honest_hash_power = (total_hash_power - byz_hash_power) // honest_miners
        for i in range(p.number_of_miners):
            if i == 1 and p.byz_class_name:
                cls = resolve_miner_class(p.byz_class_name)
                cur = cls(self._network, self.nb, byz_hash_power, self.genesis)
            else:
                cur = ETHMiner(self._network, self.nb, honest_hash_power, self.genesis)
            if i == 0:
                self._network.add_observer(cur)
            else:
                self._network.add_node(cur)
            self._network.register_periodic_task(cur.mine10ms, 1, 10, cur)


class ETHPoWWithAgent(ETHPoW):
    """Agent wrapper (ETHMinerAgent.java:162-175)."""

    def get_time_in_seconds(self) -> int:
        return self._network.time // 1000

    def get_byz_node(self) -> ETHMinerAgent:
        return self._network.all_nodes[1]


def create_agent(byz_hash_power_share: float, rd_seed: int = 0) -> ETHPoWWithAgent:
    """ETHMinerAgent.create (ETHMinerAgent.java:227-242)."""
    from ..core.registries import CITIES, builder_name

    bdl_name = builder_name(CITIES, True, 0)
    nl_name = "NetworkFixedLatency(1000)"
    params = ETHPoWParameters(bdl_name, nl_name, 10, "ETHMinerAgent", byz_hash_power_share)
    res = ETHPoWWithAgent(params)
    res.network().rd.set_seed(rd_seed)
    return res


def try_miner(builder_name_, nl_name, miner, pows, hours, runs, verbose=True):
    """Strategy evaluation sweep (ETHMiner.java:234-308)."""
    rows = []
    if verbose:
        print(
            "miner, hashrate ratio, revenue ratio, revenue, uncle rate, "
            "total revenue, avg difficulty"
        )
    miner_cls = resolve_miner_class(miner)
    for pow_ in pows:
        params = ETHPoWParameters(builder_name_, nl_name, 10, miner_cls.__name__, pow_)
        rewards: Dict[int, float] = {1: 0.0}
        ur = 0.0
        avg_diff = 0
        for i in range(1, runs + 1):
            p = ETHPoW(params)
            p.network().rd.set_seed(i)
            p.init()
            p.network().run(hours * 3600)
            limit = (5000 if hours > 30 else 0) + p.genesis.height
            base = p.network().get_node_by_id(1).head
            j = 0
            while hours > 30 and j < 5000:
                base = base.parent
                j += 1
            base.all_rewards_by_id(rewards, limit)
            ur += base.uncle_rate(limit)
            avg_diff += base.avg_difficulty(limit)
            p.get_byzantine_node().close()
        ur /= runs
        avg_diff //= runs
        tot = sum(rewards.values())
        row = {
            "miner": miner_cls.__name__,
            "pow": pow_,
            "rate": rewards[1] / tot if tot else 0.0,
            "reward": rewards[1] / runs,
            "uncle_rate": ur,
            "total": tot / runs,
            "avg_difficulty": avg_diff,
        }
        rows.append(row)
        if verbose:
            print(
                f"{miner_cls.__name__}/{nl_name}/{hours}/{runs}, {pow_:.2f}, "
                f"{row['rate']:.4f}, {row['reward']:.0f}, {ur:.4f}, "
                f"{row['total']:.0f}, {avg_diff}"
            )
    return rows

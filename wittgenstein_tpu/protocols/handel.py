"""Handel: practical multi-signature aggregation for large Byzantine
committees (arXiv:1906.05132) — the reference's flagship protocol.

Reference semantics: protocols/Handel.java.  Per-node binary levels with
reception-rank matrices (:940-948), emission lists built from ranks
(:991-1013), a periodic dissemination drumbeat (:331-343), verification as
a conditional task costing pairingTime per check with windowed scoring
(:566-630, window adaptation :150-210), fastPath bursts on level
completion (:738-742), and two attacks: byzantineSuicide (forged sigs →
blacklist, :538-559/:687-694) and hiddenByzantine (flooding the last level
with nearly-useless valid sigs, :840-917).

Bitsets are Python ints.  SigToVerify instances use identity equality,
matching Java's default equals in list remove/contains.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional

from ..core.node import Node
from ..core.params import WParameters, register_protocol
from ..core.registries import registry_network_latencies, registry_node_builders
from ..oracle.messages import Message
from ..oracle.network import Network, Protocol
from ..utils.bitset import cardinality as _card, include as _include, to_ids as _bits_to_ids
from ..utils.more_math import round_pow2

INT_MAX = 2**31 - 1


@dataclasses.dataclass
class HandelParameters(WParameters):
    node_count: int = 32768 // 1024
    threshold: float = -1
    pairing_time: int = 3
    level_wait_time: int = 50
    extra_cycle: int = 10
    dissemination_period_ms: int = 10
    fast_path: int = 10
    nodes_down: int = 0
    node_builder_name: Optional[str] = None
    network_latency_name: Optional[str] = None
    desynchronized_start: int = 0
    byzantine_suicide: bool = False
    hidden_byzantine: bool = False
    bad_nodes: Optional[int] = None  # bitset of forced-down nodes
    window_initial: int = 16
    window_minimum: int = 1
    window_maximum: int = 128
    window_increase_factor: float = 2.0
    window_decrease_factor: float = 4.0
    # batched-engine knob (no oracle effect): in-flight channel slots per
    # (receiver, level); None = the engine default.  Trades HBM for lower
    # message displacement — see BatchedHandel.CHANNEL_DEPTH
    channel_depth: Optional[int] = None
    # batched-engine knob (no oracle effect): verification-candidate slots
    # per (receiver, level); None = the engine default.  Sized from the
    # measured occupancy high-water mark by scripts/density_autotune.py —
    # bit-identical while occupancy stays under the slot count (the K
    # buffer is re-sorted every tick, so a top-K' of an under-occupied
    # top-K retains the same entries).  See BatchedHandel.CAND_SLOTS
    cand_slots: Optional[int] = None

    def __post_init__(self):
        from ._aggregation import normalize_agg_params

        normalize_agg_params(self)
        if self.node_count.bit_count() != 1:
            raise ValueError("We support only power of two nodes in this simulation")
        if self.byzantine_suicide and self.hidden_byzantine:
            raise ValueError("Only one attack at a time")

    # -- window adaptation (WindowParameters + ScoringExp, :150-210) --------
    def window_new_size(self, current: int, correct: bool) -> int:
        import math

        if correct:
            updated = math.ceil(current * self.window_increase_factor)
        else:
            updated = math.floor(current / self.window_decrease_factor)
        return max(self.window_minimum, min(self.window_maximum, updated))


class SigToVerify:
    """Identity-equality value (Handel.java:920-941)."""

    __slots__ = ("from_id", "level", "rank", "sig", "bad_sig")

    def __init__(self, from_id: int, level: int, rank: int, sig: int, bad_sig: bool):
        self.from_id = from_id
        self.level = level
        self.rank = rank
        self.sig = sig
        self.bad_sig = bad_sig


class SendSigs(Message):
    """Handel.SendSigs (:239-276)."""

    def __init__(self, sigs: int, level: "HLevel"):
        self.sigs = sigs
        self.level = level.level
        # Size = level + bit field + the signatures included + our own sig
        self._size = 1 + level.expected_sigs() // 8 + 96 * 2
        self.level_finished = level.incoming_complete()
        self.bad_sig = False
        if sigs == 0 or _card(sigs) > level.size:
            raise RuntimeError(f"bad level: {level.level}")

    def size(self) -> int:
        return self._size

    def action(self, network, from_node, to_node) -> None:
        to_node.on_new_sig(from_node, self)


class HLevel:
    """One Handel level (Handel.java:363-651)."""

    def __init__(self, node: "HNode", previous: Optional["HLevel"] = None, all_previous: int = 0):
        self._node = node
        self.finished_peers = 0
        self.outgoing_finished = False
        self.pos_in_level = 0
        self.last_agg_verified = 0
        self.total_incoming = 0
        self.verified_ind_signatures = 0
        self.to_verify_agg: List[SigToVerify] = []
        self.to_verify_ind = 0
        self.suicide_biz_after = 0 if node.params.byzantine_suicide else -1
        if previous is None:
            self.level = 0
            self.size = 1
            self.outgoing_finished = True
            self.waited_sigs = 0
            self.last_agg_verified = 1 << node.node_id
            self.verified_ind_signatures = 1 << node.node_id
            self.total_incoming = 1 << node.node_id
            self.total_outgoing = 0
            self.peers: List["HNode"] = []
        else:
            self.level = previous.level + 1
            self.waited_sigs = node.all_sigs_at_level(self.level) & ~all_previous
            self.total_outgoing = 1 << node.node_id
            self.size = _card(self.waited_sigs)
            self.peers = []

    def expected_sigs(self) -> int:
        return self.size

    def expected_nodes(self) -> List["HNode"]:
        net = self._node.network_ref
        return [net.get_node_by_id(i) for i in _bits_to_ids(self.waited_sigs)]

    def is_open(self) -> bool:
        """Level opens on timeout or once outgoing is complete (:452-467)."""
        if self.outgoing_finished:
            return False
        if self._node.network_ref.time >= (self.level - 1) * self._node.params.level_wait_time:
            return True
        if self.outgoing_complete():
            return True
        return False

    def do_cycle(self) -> None:
        if not self.is_open():
            return
        dest = self.get_remaining_peers(1)
        if dest:
            ss = SendSigs(self.total_outgoing, self)
            self._node.network_ref.send(ss, self._node, dest[0])

    def get_remaining_peers(self, peers_ct: int) -> List["HNode"]:
        res: List["HNode"] = []
        start = self.pos_in_level
        while peers_ct > 0 and not self.outgoing_finished:
            p = self.peers[self.pos_in_level]
            self.pos_in_level += 1
            if self.pos_in_level >= len(self.peers):
                self.pos_in_level = 0
            if (
                not (self.finished_peers >> p.node_id) & 1
                and not (self._node.blacklist >> p.node_id) & 1
            ):
                res.append(p)
                peers_ct -= 1
            else:
                if self.pos_in_level == start:
                    self.outgoing_finished = True
        return res

    def build_emission_list(self, emissions: List[Optional[List["HNode"]]]) -> None:
        """Emission order: peers that gave us a good reception rank first,
        ties shuffled (:505-517)."""
        if self.peers:
            raise RuntimeError()
        for ranks in emissions:
            if ranks:
                if len(ranks) > 1:
                    self._node.network_ref.rd.shuffle(ranks)
                self.peers.extend(ranks)

    def incoming_complete(self) -> bool:
        return self.waited_sigs == self.total_incoming

    def outgoing_complete(self) -> bool:
        return _card(self.total_outgoing) == self.size

    def size_if_included(self, sig: SigToVerify) -> int:
        c = sig.sig
        if not (c & self.total_incoming):
            c = c | self.total_incoming
        c |= self.verified_ind_signatures
        return _card(c)

    def create_suicide_byzantine_sig(self, max_rank: int) -> Optional[SigToVerify]:
        """Forged-signature attack feeder (:538-559)."""
        node = self._node
        reset = False
        for i in range(self.suicide_biz_after, len(self.peers)):
            p = self.peers[i]
            if p.is_down() and not (node.blacklist >> p.node_id) & 1:
                if not reset:
                    self.suicide_biz_after = i
                    reset = True
                if node.reception_ranks[p.node_id] < max_rank:
                    return SigToVerify(
                        p.node_id,
                        self.level,
                        node.reception_ranks[p.node_id],
                        self.waited_sigs,
                        True,
                    )
        if not reset:
            self.suicide_biz_after = -1
        return None

    def best_to_verify(self) -> Optional[SigToVerify]:
        """Windowed scoring: rank-based outside the window, score-based
        inside (:566-630)."""
        node = self._node
        if not self.to_verify_agg:
            return None
        if node.curr_window_size < 1:
            raise RuntimeError()

        window_index = min(s.rank for s in self.to_verify_agg)

        if self.suicide_biz_after >= 0:
            b_sig = self.create_suicide_byzantine_sig(window_index + node.curr_window_size)
            if b_sig is not None:
                self.to_verify_agg.append(b_sig)
                node.sig_queue_size += 1
                return b_sig

        cur_signature_size = _card(self.total_incoming)
        best_outside: Optional[SigToVerify] = None
        best_inside: Optional[SigToVerify] = None
        best_score_inside = 0

        removed = 0
        curated: List[SigToVerify] = []
        for stv in self.to_verify_agg:
            s = self.size_if_included(stv)
            if not (node.blacklist >> stv.from_id) & 1 and s > cur_signature_size:
                curated.append(stv)
                if stv.rank <= window_index + node.curr_window_size:
                    score = node.score(self, stv.sig)
                    if score > best_score_inside:
                        best_score_inside = score
                        best_inside = stv
                else:
                    if best_outside is None or stv.rank < best_outside.rank:
                        best_outside = stv
            else:
                removed += 1

        if removed > 0:
            node.sig_queue_size -= len(self.to_verify_agg)
            self.to_verify_agg[:] = curated
            node.sig_queue_size += len(curated)
            if node.sig_queue_size < 0:
                raise RuntimeError(f"sigQueueSize={node.sig_queue_size}")

        if best_inside is not None:
            return best_inside
        return best_outside


class HNode(Node):
    __slots__ = (
        "network_ref",
        "params",
        "start_at",
        "levels",
        "node_pairing_time",
        "reception_ranks",
        "blacklist",
        "curr_window_size",
        "added_cycle",
        "hidden_byzantine",
        "done",
        "sigs_checked",
        "sig_queue_size",
        "msg_filtered",
    )

    def __init__(self, network: Network, start_at: int, nb, byzantine: bool, params: HandelParameters):
        super().__init__(network.rd, nb, byzantine)
        self.network_ref = network
        self.params = params
        self.start_at = start_at
        self.levels: List[HLevel] = []
        self.node_pairing_time = int(max(1, params.pairing_time * self.speed_ratio))
        self.reception_ranks = [0] * params.node_count
        self.blacklist = 0
        self.curr_window_size = params.window_initial
        self.added_cycle = params.extra_cycle
        self.hidden_byzantine = (
            HiddenByzantine() if params.hidden_byzantine and not byzantine else None
        )
        self.done = False
        self.sigs_checked = 0
        self.sig_queue_size = 0
        self.msg_filtered = 0

    def __repr__(self) -> str:
        return f"HNode{{{self.node_id}}}"

    def init_level(self) -> None:
        rounded = round_pow2(self.params.node_count)
        all_previous = 0
        last = HLevel(self)
        self.levels.append(last)
        l = 1
        while 2**l <= rounded:
            all_previous |= last.waited_sigs
            last = HLevel(self, last, all_previous)
            self.levels.append(last)
            l += 1

    def dissemination(self) -> None:
        if self.done_at > 0:
            if self.added_cycle > 0:
                self.added_cycle -= 1
            else:
                return
        for sfl in self.levels:
            sfl.do_cycle()

    def has_sig_to_verify(self) -> bool:
        return self.sig_queue_size != 0

    def total_sig_size(self) -> int:
        last = self.levels[-1]
        return _card(last.total_outgoing) + _card(last.total_incoming)

    def level_of(self, dest: "HNode") -> int:
        for i in range(len(self.levels) - 1, -1, -1):
            if (self.levels[i].waited_sigs >> dest.node_id) & 1:
                return i
        raise RuntimeError()

    def score(self, l: HLevel, sig: int) -> int:
        """Added-signature count if verified (:585-600)."""
        if _card(l.last_agg_verified) >= l.expected_sigs():
            return 0
        if not (l.last_agg_verified & sig):
            return _card(l.last_agg_verified) + _card(sig)
        with_indiv = l.verified_ind_signatures | sig
        return max(0, _card(with_indiv) - _card(l.last_agg_verified))

    def all_sigs_at_level(self, round_: int) -> int:
        """Binary-tree membership trick (Handel.java:634-647)."""
        from ._aggregation import all_sigs_at_level

        return all_sigs_at_level(self.node_id, round_, self.params.node_count)

    def update_verified_signatures(self, vs: SigToVerify) -> None:
        """Verification completion (:686-750)."""
        if vs.bad_sig:
            self.blacklist |= 1 << vs.from_id
            if not self.params.byzantine_suicide:
                raise RuntimeError("We should not have invalid signatures in this scenario")
            return

        vsl = self.levels[vs.level]
        if not _include(vsl.waited_sigs, vs.sig):
            raise RuntimeError("bad signature received")

        vsl.to_verify_ind &= ~(1 << vs.from_id)
        try:
            vsl.to_verify_agg.remove(vs)
        except ValueError:
            pass

        vsl.verified_ind_signatures |= 1 << vs.from_id

        improved = False
        if not (vsl.total_incoming >> vs.from_id) & 1:
            vsl.total_incoming |= 1 << vs.from_id
            improved = True

        all_ = vs.sig | vsl.verified_ind_signatures
        if _card(all_) > _card(vsl.verified_ind_signatures):
            improved = True
            if vsl.last_agg_verified & vs.sig:
                vsl.last_agg_verified = 0
            vsl.last_agg_verified |= vs.sig
            vsl.total_incoming = vsl.last_agg_verified | vsl.verified_ind_signatures

        if not improved:
            return

        just_completed = vsl.incoming_complete()

        cur = 0
        for l in self.levels:
            if l.level > vsl.level:
                l.total_outgoing = cur
                if (
                    just_completed
                    and self.params.fast_path > 0
                    and not l.outgoing_finished
                    and l.outgoing_complete()
                ):
                    peers = l.get_remaining_peers(self.params.fast_path)
                    send_sigs = SendSigs(l.total_outgoing, l)
                    self.network_ref.send(send_sigs, self, peers)
            cur |= l.total_incoming

        if self.done_at == 0 and _card(cur) >= self.params.threshold:
            self.done_at = self.network_ref.time

    def on_new_sig(self, from_node: "HNode", ssigs: SendSigs) -> None:
        """(:752-786)"""
        if self.done_at > 0:
            self.msg_filtered += 1
            return
        if self.network_ref.time < self.start_at or (self.blacklist >> from_node.node_id) & 1:
            return

        l = self.levels[ssigs.level]
        if not _include(l.waited_sigs, ssigs.sigs):
            raise RuntimeError("bad signatures received")
        cs = ssigs.sigs & l.waited_sigs
        if cs != ssigs.sigs or ssigs.sigs == 0:
            raise RuntimeError("bad message")

        if ssigs.level_finished:
            l.finished_peers |= 1 << from_node.node_id
        if not (l.verified_ind_signatures >> from_node.node_id) & 1:
            l.to_verify_ind |= 1 << from_node.node_id

        self.sig_queue_size += 1
        l.to_verify_agg.append(
            SigToVerify(
                from_node.node_id,
                l.level,
                self.reception_ranks[from_node.node_id],
                cs,
                ssigs.bad_sig,
            )
        )

    def check_sigs(self) -> None:
        """(:792-837)"""
        by_levels: List[SigToVerify] = []
        for l in self.levels:
            ss = l.best_to_verify()
            if ss is not None:
                by_levels.append(ss)
        if not by_levels:
            return

        best = by_levels[self.network_ref.rd.next_int(len(by_levels))]

        if self.hidden_byzantine is not None and best.level == len(self.levels) - 1:
            best = self.hidden_byzantine.attack(self, best)

        l = self.levels[best.level]
        new_size = self.params.window_new_size(self.curr_window_size, not best.bad_sig)
        self.curr_window_size = min(new_size, l.size)

        # push to the end of the ranking, with Java int overflow clamp
        self.reception_ranks[best.from_id] += self.params.node_count
        if self.reception_ranks[best.from_id] > INT_MAX:
            self.reception_ranks[best.from_id] = INT_MAX

        self.sigs_checked += 1
        f_best = best
        self.network_ref.register_task(
            lambda: self.update_verified_signatures(f_best),
            self.network_ref.time + self.node_pairing_time,
            self,
        )


class HiddenByzantine:
    """Flood the last level with valid but nearly-useless signatures
    (:840-917)."""

    def __init__(self):
        self.no_byzantine_peers = False
        self.last: Optional[SigToVerify] = None

    def first_byzantine(self, t: HNode, l: HLevel) -> Optional[HNode]:
        best = None
        best_rank = INT_MAX
        for p in l.peers:
            if (
                p.is_down()
                and t.reception_ranks[p.node_id] < best_rank
                and not (l.total_incoming >> p.node_id) & 1
            ):
                best_rank = t.reception_ranks[p.node_id]
                best = p
                if best_rank == 0:
                    return p
        return best

    def attack(self, target: HNode, current_best: SigToVerify) -> SigToVerify:
        if self.no_byzantine_peers:
            return current_best
        if self.last is current_best:
            self.last = None
            return current_best

        l = target.levels[current_best.level]
        if self.last is not None:
            if any(s is self.last for s in l.to_verify_agg):
                return current_best
            if not (l.total_incoming >> self.last.from_id) & 1:
                raise RuntimeError("byz signature pruned!")
            self.last = None

        first_byz = self.first_byzantine(target, l)
        if first_byz is None:
            self.no_byzantine_peers = True
            return current_best

        if target.reception_ranks[first_byz.node_id] >= current_best.rank:
            return current_best

        bad = SigToVerify(
            first_byz.node_id,
            l.level,
            target.reception_ranks[first_byz.node_id],
            1 << first_byz.node_id,
            False,
        )
        l.to_verify_agg.append(bad)
        target.sig_queue_size += 1

        new_best = l.best_to_verify()
        if new_best is not bad:
            self.last = bad
        return new_best


@register_protocol("Handel", HandelParameters)
class Handel(Protocol):
    def __init__(self, params: HandelParameters):
        self.params = params
        self._network: Network[HNode] = Network()
        self._network.set_network_latency(
            registry_network_latencies.get_by_name(params.network_latency_name)
        )

    def __str__(self) -> str:
        p = self.params
        return (
            f"Handel, nodes={p.node_count}, threshold={p.threshold}"
            f", pairing={p.pairing_time}ms, levelWaitTime={p.level_wait_time}ms"
            f", period={p.dissemination_period_ms}ms"
            f", acceleratedCallsCount={p.fast_path}, dead nodes={p.nodes_down}"
            f", builder={p.node_builder_name}"
        )

    def copy(self) -> "Handel":
        return Handel(self.params)

    def init(self) -> None:
        p = self.params
        nb = registry_node_builders.get_by_name(p.node_builder_name)

        if p.bad_nodes is not None:
            bad_nodes = p.bad_nodes
        else:
            bad = Network.choose_bad_nodes(self._network.rd, p.node_count, p.nodes_down)
            bad_nodes = 0
            for b in bad:
                bad_nodes |= 1 << b

        for i in range(p.node_count):
            start_at = (
                0
                if p.desynchronized_start == 0
                else self._network.rd.next_int(p.desynchronized_start)
            )
            byz = (p.byzantine_suicide or p.hidden_byzantine) and bool(
                (bad_nodes >> i) & 1
            )
            n = HNode(self._network, start_at, nb, byz, p)
            if (bad_nodes >> i) & 1:
                n.stop()
            self._network.add_node(n)

        for n in self._network.all_nodes:
            n.init_level()
            if not n.is_down():
                self._network.register_periodic_task(
                    n.dissemination, n.start_at + 1, p.dissemination_period_ms, n
                )
                self._network.register_conditional_task(
                    n.check_sigs,
                    n.start_at + 1,
                    n.node_pairing_time,
                    n,
                    n.has_sig_to_verify,
                    lambda n=n: not n.done,
                )

        self._set_receiving_ranks()

        # emission lists: contact first the peers that rank us well (:991-1013)
        for sender in self._network.all_nodes:
            if sender.is_down():
                continue
            for l in sender.levels:
                emission_list: List[Optional[List[HNode]]] = [None] * p.node_count
                for receiver in l.expected_nodes():
                    rec_rank = receiver.reception_ranks[sender.node_id]
                    if emission_list[rec_rank] is None:
                        emission_list[rec_rank] = []
                    emission_list[rec_rank].append(receiver)
                l.build_emission_list(emission_list)

    def _set_receiving_ranks(self) -> None:
        """One shared, repeatedly-shuffled list — exact RNG stream parity
        with setReceivingRanks (:940-948)."""
        expected = list(self._network.all_nodes)
        for n in self._network.all_nodes:
            self._network.rd.shuffle(expected)
            for i, e in enumerate(expected):
                n.reception_ranks[e.node_id] = i

    def network(self) -> Network:
        return self._network

    @staticmethod
    def new_cont_if():
        def cont(p: "Handel") -> bool:
            for n in p.network().live_nodes():
                if n.done_at == 0 or n.added_cycle > 0:
                    return True
            return False

        return cont

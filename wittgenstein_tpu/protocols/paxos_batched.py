"""Batched classic Paxos: acceptors and proposers as vectorized per-slot
kernels; proposer timeouts as explicit-arrival self-messages.

Reference semantics: protocols/Paxos.java (AcceptorNode :153-207,
ProposerNode :209-339, seq scheme :313-338) via the oracle port
`protocols/paxos.py`.

TPU-first notes:

  * every per-node field is a scalar column; Optional[int] becomes -1;
  * `registerTask(onTimeout, ...)` becomes a size-0 TIMEOUT self-message
    with an explicit arrival (the engine's sendArriveAt path), so the
    protocol stays pure-message (TICK_INTERVAL None — the engine skips
    idle ms);
  * in-progress counters are capped at `majority`, so a crossing fires
    exactly once (the oracle's `count < majority` entry guard);
  * same-tick batches of PROPOSE/COMMIT at one acceptor are all evaluated
    against the pre-tick acceptor state (the oracle orders them LIFO
    within the ms); the acceptor state then advances with the max-seq
    winner.  AGREE bookkeeping takes the same-tick max of (acceptedSeq,
    acceptedVal) pairs via a packed scatter-max.
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
import numpy as np

from ..core.node import build_node_columns
from ..core.registries import registry_network_latencies
from ..engine import BatchedNetwork, BatchedProtocol, Emission
from .paxos import MAX_VAL, Paxos, PaxosParameters

NONE = jnp.int32(-1)
# packed (acceptedSeq, acceptedVal) scatter-max key; val < MAX_VAL=1000 < 2048
VAL_PACK = 2048


class BatchedPaxos(BatchedProtocol):
    MSG_TYPES = ["PROPOSE", "REJECT", "AGREE", "COMMIT", "ACCEPT", "REJECT2", "TIMEOUT"]
    PAYLOAD_WIDTH = 3  # AGREE carries (yourSeq, acceptedSeq, acceptedVal)
    TICK_INTERVAL = None

    def __init__(self, params: PaxosParameters, roles: dict):
        self.params = params
        self.majority = params.acceptor_count // 2 + 1
        self.n_acc = params.acceptor_count
        self.n_prop = params.proposer_count
        self.n_nodes = self.n_acc + self.n_prop
        self.is_acc = jnp.asarray(roles["is_acc"])
        self.is_prop = jnp.asarray(roles["is_prop"])
        self.rank = jnp.asarray(roles["rank"], jnp.int32)
        self.value_proposed = jnp.asarray(roles["value_proposed"], jnp.int32)
        self.acc_ids = jnp.asarray(roles["acc_ids"], jnp.int32)
        self.prop_ids = jnp.asarray(roles["prop_ids"], jnp.int32)

    def msg_size(self, mtype: int) -> int:
        return 0 if self.MSG_TYPES[mtype] == "TIMEOUT" else 1

    def proto_init(self, n_nodes: int):
        zi = lambda: jnp.zeros(n_nodes, jnp.int32)
        none = lambda: jnp.full(n_nodes, NONE, jnp.int32)
        # the init-time startNextProposal is pre-applied: first seq is
        # proposerCount + rank (seqAccepted=0, seqIP=0 path, :329-333);
        # initial_emissions builds the matching PROPOSE + TIMEOUT rows
        first_seq = jnp.where(
            self.is_prop, self.params.proposer_count + self.rank, 0
        ).astype(jnp.int32)
        return {
            # acceptor columns (Paxos.java:153-160)
            "max_agreed": none(),
            "acc_seq": none(),
            "acc_val": none(),
            # proposer columns (:209-240)
            "seq_ip": first_seq,
            "prop_ip": self.is_prop,
            "seq_accepted": zi(),
            "asi": none(),  # acceptedSeqIP
            "avi": none(),  # acceptedValIP
            "agree_ip": zi(),
            "rej1_ip": zi(),
            "accept_ip": zi(),
            "rej2_ip": zi(),
            "value_accepted": none(),
            "agree_count": zi(),
            "rej1_count": zi(),
            "rej2_count": zi(),
            "timeout_count": zi(),
        }

    def _proposal_emissions(self, seq_ip, mask, t):
        """PROPOSE to every acceptor + the timeout self-message, shared by
        the init path and round restarts (sent at t+1; timeout at
        t+1+timeout, :329-338)."""
        ka = self.n_prop * self.n_acc
        em_prop = Emission(
            mask=jnp.repeat(mask[self.prop_ids], self.n_acc),
            from_idx=jnp.repeat(self.prop_ids, self.n_acc),
            to_idx=jnp.tile(self.acc_ids, self.n_prop),
            mtype=self.mtype("PROPOSE"),
            payload=jnp.stack(
                [
                    jnp.repeat(seq_ip[self.prop_ids], self.n_acc),
                    jnp.zeros(ka, jnp.int32),
                    jnp.zeros(ka, jnp.int32),
                ],
                axis=1,
            ),
        )
        em_tmo = Emission(
            mask=mask[self.prop_ids],
            from_idx=self.prop_ids,
            to_idx=self.prop_ids,
            mtype=self.mtype("TIMEOUT"),
            payload=jnp.stack(
                [
                    seq_ip[self.prop_ids],
                    jnp.zeros(self.n_prop, jnp.int32),
                    jnp.zeros(self.n_prop, jnp.int32),
                ],
                axis=1,
            ),
            arrival=jnp.broadcast_to(
                t + 1 + self.params.timeout, (self.n_prop,)
            ).astype(jnp.int32),
        )
        return [em_prop, em_tmo]

    # -- proposer round start (startNextProposal, :313-338) ------------------
    def _start_proposals(self, state, mask, proto):
        """Reset in-progress state, pick the next seq, PROPOSE to every
        acceptor and arm the timeout self-message."""
        pc = self.params.proposer_count
        gap = proto["seq_accepted"] % pc
        cand = proto["seq_accepted"] + pc - gap + self.rank
        new_seq = jnp.where(cand > proto["seq_ip"], cand, proto["seq_ip"] + pc)
        seq_ip = jnp.where(mask, new_seq, proto["seq_ip"])
        proto = dict(
            proto,
            seq_ip=seq_ip,
            prop_ip=jnp.where(mask, True, proto["prop_ip"]),
            asi=jnp.where(mask, NONE, proto["asi"]),
            avi=jnp.where(mask, NONE, proto["avi"]),
            agree_ip=jnp.where(mask, 0, proto["agree_ip"]),
            rej1_ip=jnp.where(mask, 0, proto["rej1_ip"]),
            accept_ip=jnp.where(mask, 0, proto["accept_ip"]),
            rej2_ip=jnp.where(mask, 0, proto["rej2_ip"]),
        )
        return proto, self._proposal_emissions(seq_ip, mask, state.time)

    def initial_emissions(self, net, state):
        """init: every proposer's first PROPOSE (sent at t=1) and its
        timeout — the state side is pre-baked in proto_init."""
        return self._proposal_emissions(
            state.proto["seq_ip"], self.is_prop, state.time
        )

    def deliver(self, net, state, deliver_mask):
        p = self.params
        proto = dict(state.proto)
        n, c = self.n_nodes, deliver_mask.shape[0]
        t = state.time
        ids = jnp.arange(n, dtype=jnp.int32)
        to, frm = state.msg_to, state.msg_from
        seq_p = state.msg_payload[:, 0]
        p1 = state.msg_payload[:, 1]
        p2 = state.msg_payload[:, 2]
        m_ = lambda name: deliver_mask & (state.msg_type == self.mtype(name))
        is_pro, is_rej, is_agr = m_("PROPOSE"), m_("REJECT"), m_("AGREE")
        is_com, is_acc, is_rj2 = m_("COMMIT"), m_("ACCEPT"), m_("REJECT2")
        is_tmo = m_("TIMEOUT")
        emissions = []

        # ---- acceptors: onPropose (:163-177) ------------------------------
        ma = proto["max_agreed"]
        agree = is_pro & (seq_p > ma[to])
        reject = is_pro & (seq_p < ma[to])
        emissions.append(
            Emission(  # per-slot replies against pre-tick acceptor state
                mask=agree | reject,
                from_idx=to,
                to_idx=frm,
                mtype=jnp.where(agree, self.mtype("AGREE"), self.mtype("REJECT")),
                payload=jnp.stack(
                    [
                        seq_p,
                        jnp.where(agree, proto["acc_seq"][to], ma[to]),
                        jnp.where(agree, proto["acc_val"][to], 0),
                    ],
                    axis=1,
                ),
            )
        )
        proto["max_agreed"] = ma.at[to].max(
            jnp.where(agree, seq_p, NONE), mode="drop"
        )

        # ---- acceptors: onCommit (:179-192) -------------------------------
        ok_com = is_com & (seq_p == ma[to]) & (
            (proto["acc_val"][to] == NONE) | (proto["acc_val"][to] == p1)
        )
        rj_com = is_com & ~ok_com
        emissions.append(
            Emission(
                mask=ok_com | rj_com,
                from_idx=to,
                to_idx=frm,
                mtype=jnp.where(ok_com, self.mtype("ACCEPT"), self.mtype("REJECT2")),
                payload=jnp.stack([seq_p, jnp.where(ok_com, 0, ma[to]), jnp.zeros(c, jnp.int32)], axis=1),
            )
        )
        proto["acc_val"] = proto["acc_val"].at[to].max(
            jnp.where(ok_com, p1, NONE), mode="drop"
        )
        proto["acc_seq"] = proto["acc_seq"].at[to].max(
            jnp.where(ok_com, seq_p, NONE), mode="drop"
        )

        # ---- proposers: count replies for the current seq -----------------
        live = proto["prop_ip"][to] & (seq_p == proto["seq_ip"][to])

        def count(mask_slots, col, cap=True):
            arr = jnp.zeros(n, jnp.int32).at[to].add(
                (mask_slots & live).astype(jnp.int32), mode="drop"
            )
            new = proto[col] + arr
            return jnp.minimum(new, self.majority) if cap else new

        old_agree, old_rej1 = proto["agree_ip"], proto["rej1_ip"]
        old_accept, old_rej2 = proto["accept_ip"], proto["rej2_ip"]
        proto["agree_ip"] = count(is_agr, "agree_ip")
        proto["rej1_ip"] = count(is_rej, "rej1_ip")
        proto["accept_ip"] = count(is_acc, "accept_ip")
        proto["rej2_ip"] = count(is_rj2, "rej2_ip")

        # AGREE (acceptedSeq, acceptedVal) bookkeeping: same-tick max
        # (:255-259); gated on the pre-majority count like the oracle's
        # `agree_count_ip < majority` entry guard — stragglers arriving
        # after the COMMIT went out must not rewrite the committed value
        has_prev = is_agr & live & (p1 != NONE) & (old_agree[to] < self.majority)
        pack = jnp.full(n, -1, jnp.int32).at[to].max(
            jnp.where(has_prev, p1 * VAL_PACK + jnp.clip(p2, 0, VAL_PACK - 1), -1),
            mode="drop",
        )
        better = (pack >= 0) & ((proto["asi"] == NONE) | (pack // VAL_PACK > proto["asi"]))
        proto["asi"] = jnp.where(better, pack // VAL_PACK, proto["asi"])
        proto["avi"] = jnp.where(better, pack % VAL_PACK, proto["avi"])

        # rejection seq feedback: seqAccepted = max(seqAccepted, serverSeq)
        rej_seq = jnp.zeros(n, jnp.int32).at[to].max(
            jnp.where((is_rej | is_rj2) & live, p1, 0), mode="drop"
        )

        maj = self.majority
        cross = lambda old, new: (old < maj) & (new >= maj)
        agree_x = cross(old_agree, proto["agree_ip"])
        rej1_x = cross(old_rej1, proto["rej1_ip"])
        accept_x = cross(old_accept, proto["accept_ip"])
        rej2_x = cross(old_rej2, proto["rej2_ip"])

        # onAgree majority: commit the learned or own value (:260-268)
        proto["agree_count"] = proto["agree_count"] + agree_x.astype(jnp.int32)
        avi = jnp.where(
            agree_x & (proto["avi"] == NONE), self.value_proposed, proto["avi"]
        )
        proto["avi"] = avi
        emissions.append(
            Emission(
                mask=jnp.repeat(agree_x[self.prop_ids], self.n_acc),
                from_idx=jnp.repeat(self.prop_ids, self.n_acc),
                to_idx=jnp.tile(self.acc_ids, self.n_prop),
                mtype=self.mtype("COMMIT"),
                payload=jnp.stack(
                    [
                        jnp.repeat(proto["seq_ip"][self.prop_ids], self.n_acc),
                        jnp.repeat(avi[self.prop_ids], self.n_acc),
                        jnp.zeros(self.n_prop * self.n_acc, jnp.int32),
                    ],
                    axis=1,
                ),
            )
        )

        # onAccept majority: value accepted, node done (:269-280)
        newly_done = accept_x & (proto["value_accepted"] == NONE)
        proto["value_accepted"] = jnp.where(newly_done, avi, proto["value_accepted"])
        proto["prop_ip"] = proto["prop_ip"] & ~(accept_x | rej1_x | rej2_x)
        state = state._replace(
            done_at=jnp.where(newly_done, jnp.maximum(t, 1), state.done_at)
        )

        # timeout while still in progress (:305-310)
        tmo_fire = jnp.zeros(n, bool).at[to].max(is_tmo & live, mode="drop")
        tmo_fire = tmo_fire & proto["prop_ip"] & ~(agree_x | accept_x)
        proto["timeout_count"] = proto["timeout_count"] + tmo_fire.astype(jnp.int32)

        # rejected or timed out -> next round (:244-249, :281-288)
        proto["rej1_count"] = proto["rej1_count"] + rej1_x.astype(jnp.int32)
        proto["rej2_count"] = proto["rej2_count"] + rej2_x.astype(jnp.int32)
        proto["seq_accepted"] = jnp.where(
            rej1_x | rej2_x,
            jnp.maximum(proto["seq_accepted"], rej_seq),
            proto["seq_accepted"],
        )
        restart = (rej1_x | rej2_x | tmo_fire) & (proto["value_accepted"] == NONE)
        proto["prop_ip"] = proto["prop_ip"] & ~restart
        proto, ems2 = self._start_proposals(state, restart, proto)
        emissions += ems2

        return state._replace(proto=proto), emissions

    def all_done(self, state):
        return jnp.all(
            jnp.where(self.is_prop, state.proto["value_accepted"] != NONE, True)
        )


def make_paxos(
    params: Optional[PaxosParameters] = None, capacity: int = 1 << 11, seed: int = 0
):
    """Host-side construction from the oracle's node population (same
    JavaRandom stream: positions AND each proposer's valueProposed)."""
    params = params or PaxosParameters()
    oracle = Paxos(params)
    oracle.init()
    nodes = oracle.network().all_nodes
    n = len(nodes)
    from .paxos import AcceptorNode, ProposerNode

    roles = {
        "is_acc": np.array([isinstance(nd, AcceptorNode) for nd in nodes]),
        "is_prop": np.array([isinstance(nd, ProposerNode) for nd in nodes]),
        "rank": np.array([getattr(nd, "rank", 0) for nd in nodes], dtype=np.int32),
        "value_proposed": np.array(
            [getattr(nd, "value_proposed", 0) for nd in nodes], dtype=np.int32
        ),
        "acc_ids": np.array(
            [nd.node_id for nd in nodes if isinstance(nd, AcceptorNode)], np.int32
        ),
        "prop_ids": np.array(
            [nd.node_id for nd in nodes if isinstance(nd, ProposerNode)], np.int32
        ),
    }
    latency = registry_network_latencies.get_by_name(params.latency)
    city_index = getattr(latency, "city_index", None)
    cols = build_node_columns(nodes, city_index)
    proto = BatchedPaxos(params, roles)
    net = BatchedNetwork(proto, latency, n, capacity=capacity)
    state = net.init_state(cols, seed=seed, proto=proto.proto_init(n))
    return net, state

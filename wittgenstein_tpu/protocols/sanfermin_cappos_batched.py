"""Batched SanFerminCappos: the San Fermin variant with multi-candidate
swaps, per-level signature caches and level timeouts.

Reference semantics: protocols/SanFerminCappos.java (onSwap :201-241,
tryNextNodes + timeout :248-296, goNextLevel with the live futur-skip
recursion :306-344, totalNumberOfSigs :351-358, putCachedSig threshold
check :382-393) via the oracle port `protocols/sanfermin_cappos.py`.

Differences from the batched SanFerminSignature worth naming:

  * there is no pending set at all — every Swap(level, value) at the
    receiver's level from a candidate triggers the transition, whether it
    was a request (wantReply) or a reply;
  * the aggregate is DERIVED, not stored: totalNumberOfSigs(l) = 1 + the
    sum over levels >= l of the best cached value — a masked row-sum over
    the [N, W+1] cache matrix;
  * goNextLevel's futur-skip recursion is LIVE here (case-A caching fills
    levels ahead), so the descent is a bounded unrolled loop over the
    log2(N) levels with shrinking masks.

Shared machinery (XOR candidate blocks, position->partner bijection, the
single live timeout approximation) comes from sanfermin_batched."""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
import numpy as np

from ..core.node import build_node_columns
from ..core.registries import registry_network_latencies
from ..engine import BatchedNetwork, BatchedProtocol, Emission
from ..engine.rng import hash32
from ..utils.more_math import log2
from .sanfermin_cappos import SanFerminCappos, SanFerminParameters


class BatchedSanFerminCappos(BatchedProtocol):
    MSG_TYPES = ["SWAP"]
    PAYLOAD_WIDTH = 3  # (level, value, want_reply)
    TICK_INTERVAL = 1

    def __init__(self, params: SanFerminParameters):
        self.params = params
        self.n_nodes = params.node_count
        self.w = log2(self.n_nodes)
        assert 1 << self.w == self.n_nodes, "node_count must be a power of two"
        # contacts per send: the exact candidate + candidate_count walkers,
        # capped at the largest block
        self.k = 1 + min(params.candidate_count, self.n_nodes // 2)

    def msg_size(self, mtype: int) -> int:
        return 4 + self.params.signature_size  # Swap.size (:48-50)

    def proto_init(self, n_nodes: int, seed: int = 0):
        w = self.w
        return {
            "cpl": jnp.full(n_nodes, w - 1, jnp.int32),
            "done": jnp.zeros(n_nodes, bool),
            "thr_done": jnp.zeros(n_nodes, bool),
            "thr_at": jnp.zeros(n_nodes, jnp.int32),
            "swapping": jnp.zeros(n_nodes, bool),
            "swap_lvl": jnp.zeros(n_nodes, jnp.int32),
            "swap_val": jnp.zeros(n_nodes, jnp.int32),
            "swap_t": jnp.zeros(n_nodes, jnp.int32),
            "cache_best": jnp.zeros((n_nodes, w + 1), jnp.int32),
            "cache_any": jnp.zeros((n_nodes, w + 1), bool),
            "cursor": jnp.full(n_nodes, self.k, jnp.int32),
            "tmo_t": jnp.full(n_nodes, 1 + self.params.timeout, jnp.int32),
            "tmo_lvl": jnp.full(n_nodes, w - 1, jnp.int32),
        }

    # -- shared XOR-block candidate walk (see sanfermin_batched) -------------
    def _bs(self, cpl):
        return (jnp.int32(1) << (self.w - 1 - cpl)).astype(jnp.int32)

    def _partner(self, seed, ids, cpl, position):
        bs = self._bs(cpl)
        x = hash32(seed, ids, cpl, jnp.int32(0x5AFE)) & (bs - 1)
        q = position - 1
        p = q + (q >= x).astype(jnp.int32)
        r = jnp.where(position == 0, 0, p ^ x)
        return ids ^ (bs + r), position < bs

    def _total_sigs(self, proto, level):
        """totalNumberOfSigs(level): own sig + best cached per level >= l
        (:351-358)."""
        lr = jnp.arange(self.w + 1, dtype=jnp.int32)
        m = lr[None, :] >= level[:, None]
        return 1 + jnp.sum(jnp.where(m, proto["cache_best"], 0), axis=1)

    def _send_swaps(self, state, mask, proto):
        """tryNextNodes: Swap(cpl, totalSigs(cpl+1), wantReply=True) to the
        next k candidates; arm the (single live) timeout."""
        n = self.n_nodes
        ids = jnp.arange(n, dtype=jnp.int32)
        cpl, cursor = proto["cpl"], proto["cursor"]
        value = self._total_sigs(proto, cpl + 1)
        rows_mask, rows_to = [], []
        for j in range(self.k):
            partner, in_block = self._partner(state.seed, ids, cpl, cursor + j)
            rows_mask.append(mask & in_block)
            rows_to.append(partner)
        em = Emission(
            mask=jnp.stack(rows_mask, 1).reshape(-1),
            from_idx=jnp.repeat(ids, self.k),
            to_idx=jnp.clip(jnp.stack(rows_to, 1).reshape(-1), 0, n - 1),
            mtype=self.mtype("SWAP"),
            payload=jnp.stack(
                [
                    jnp.repeat(cpl[:, None], self.k, 1).reshape(-1),
                    jnp.repeat(value[:, None], self.k, 1).reshape(-1),
                    jnp.ones(n * self.k, jnp.int32),
                ],
                axis=1,
            ),
        )
        proto = dict(
            proto,
            cursor=jnp.where(mask, cursor + self.k, cursor),
            tmo_t=jnp.where(mask, state.time + 1 + self.params.timeout, proto["tmo_t"]),
            tmo_lvl=jnp.where(mask, cpl, proto["tmo_lvl"]),
        )
        return proto, em

    def initial_emissions(self, net, state):
        """The pre-applied t=1 goNextLevel sends (bookkeeping in proto_init)."""
        n = self.n_nodes
        ids = jnp.arange(n, dtype=jnp.int32)
        cpl = state.proto["cpl"]
        rows_mask, rows_to = [], []
        for j in range(self.k):
            partner, in_block = self._partner(
                state.seed, ids, cpl, jnp.full(n, j, jnp.int32)
            )
            rows_mask.append(in_block)
            rows_to.append(partner)
        return [
            Emission(
                mask=jnp.stack(rows_mask, 1).reshape(-1),
                from_idx=jnp.repeat(ids, self.k),
                to_idx=jnp.clip(jnp.stack(rows_to, 1).reshape(-1), 0, n - 1),
                mtype=self.mtype("SWAP"),
                payload=jnp.stack(
                    [
                        jnp.repeat(cpl[:, None], self.k, 1).reshape(-1),
                        jnp.ones(n * self.k, jnp.int32),  # totalSigs = 1 at init
                        jnp.ones(n * self.k, jnp.int32),
                    ],
                    axis=1,
                ),
            )
        ]

    # -- message handling (onSwap, :201-241) ---------------------------------
    def deliver(self, net, state, deliver_mask):
        p = self.params
        proto = dict(state.proto)
        n, c = self.n_nodes, deliver_mask.shape[0]
        t = state.time
        to, frm = state.msg_to, state.msg_from
        lvl_p = jnp.clip(state.msg_payload[:, 0], 0, self.w)
        val_p = state.msg_payload[:, 1]
        want = state.msg_payload[:, 2] == 1
        slot = jnp.arange(c, dtype=jnp.int32)

        is_swap = deliver_mask & (state.msg_type == self.mtype("SWAP"))
        cpl, done = proto["cpl"], proto["done"]
        xorv = to ^ frm
        bs_p = (jnp.int32(1) << jnp.clip(self.w - 1 - lvl_p, 0, self.w)).astype(jnp.int32)
        is_cand = (xorv >= bs_p) & (xorv < 2 * bs_p)

        mismatch = done[to] | (lvl_p != cpl[to])
        cached = proto["cache_any"][to, lvl_p]
        # case A: stale/done receiver — cached reply or cache the offer
        a_reply = is_swap & mismatch & want & cached
        a_store = is_swap & mismatch & ~(want & cached) & is_cand
        # case B: level match — reply when asked, then maybe transition
        b_reply = is_swap & ~mismatch & want
        trigger = is_swap & ~mismatch & is_cand & ~proto["swapping"][to] & ~done[to]

        # replies (both cases ship want_reply=False); case B answers with
        # totalNumberOfSigs(swap.level) — the level itself, not level+1
        # (:224-227)
        rep_val = jnp.where(
            a_reply,
            proto["cache_best"][to, lvl_p],
            self._total_sigs(proto, cpl)[to],
        )
        reply_em = Emission(
            mask=a_reply | b_reply,
            from_idx=to,
            to_idx=frm,
            mtype=self.mtype("SWAP"),
            payload=jnp.stack(
                [lvl_p, rep_val, jnp.zeros(c, jnp.int32)], axis=1
            ),
        )

        # case-A cache append: scatter-max per (node, level) + threshold
        proto["cache_best"] = proto["cache_best"].at[to, lvl_p].max(
            jnp.where(a_store, val_p, 0), mode="drop"
        )
        proto["cache_any"] = proto["cache_any"].at[to, lvl_p].max(
            a_store, mode="drop"
        )
        got_store = jnp.zeros(n, bool).at[to].max(a_store, mode="drop")
        thr = self._total_sigs(proto, cpl) >= p.threshold
        thr_now = got_store & thr & ~proto["thr_done"] & ~done
        proto["thr_done"] = proto["thr_done"] | thr_now
        proto["thr_at"] = jnp.where(thr_now, t + 2 * p.pairing_time, proto["thr_at"])

        # transition: lowest-slot winner per node
        twin = jnp.full(n, c, jnp.int32)
        twin = twin.at[to].min(jnp.where(trigger, slot, c), mode="drop")
        has_t = twin < c
        tslot = jnp.clip(twin, 0, c - 1)
        proto["swapping"] = proto["swapping"] | has_t
        proto["swap_lvl"] = jnp.where(has_t, lvl_p[tslot], proto["swap_lvl"])
        proto["swap_val"] = jnp.where(has_t, val_p[tslot], proto["swap_val"])
        proto["swap_t"] = jnp.where(has_t, t + p.pairing_time, proto["swap_t"])

        return state._replace(proto=proto), [reply_em]

    # -- per-tick: commit, descend (with futur skips), timeouts --------------
    def tick(self, net, state):
        p = self.params
        proto = dict(state.proto)
        t = state.time
        n = self.n_nodes
        w = self.w
        lr = jnp.arange(w + 1, dtype=jnp.int32)

        # commit: putCachedSig(swapLvl, swapVal) then goNextLevel
        commit = proto["swapping"] & (t >= proto["swap_t"]) & (proto["swap_t"] > 0)
        proto["cache_best"] = jnp.where(
            commit[:, None] & (lr[None, :] == proto["swap_lvl"][:, None]),
            jnp.maximum(proto["cache_best"], proto["swap_val"][:, None]),
            proto["cache_best"],
        )
        proto["cache_any"] = proto["cache_any"] | (
            commit[:, None] & (lr[None, :] == proto["swap_lvl"][:, None])
        )

        # goNextLevel with the futur-skip recursion, unrolled over levels
        active = commit
        descended = jnp.zeros(n, bool)
        for _ in range(w + 1):
            thr = self._total_sigs(proto, proto["cpl"]) >= p.threshold
            thr_now = active & thr & ~proto["thr_done"]
            proto["thr_done"] = proto["thr_done"] | thr_now
            proto["thr_at"] = jnp.where(
                thr_now, t + 2 * p.pairing_time, proto["thr_at"]
            )
            finish = active & (proto["cpl"] == 0)
            proto["done"] = proto["done"] | finish
            state = state._replace(
                done_at=jnp.where(finish, t + 2 * p.pairing_time, state.done_at)
            )
            active = active & ~finish
            proto["cpl"] = jnp.where(active, proto["cpl"] - 1, proto["cpl"])
            proto["swapping"] = proto["swapping"] & ~active
            proto["cursor"] = jnp.where(active, 0, proto["cursor"])
            descended = descended | active
            # continue descending only through already-cached levels
            active = active & proto["cache_any"][
                jnp.arange(n, dtype=jnp.int32), jnp.clip(proto["cpl"], 0, w)
            ]
        proto["swapping"] = proto["swapping"] & ~commit

        # timeout: re-pick while the level is unchanged (:282-291)
        tmo = (
            ~proto["done"]
            & (proto["tmo_t"] > 0)
            & (t >= proto["tmo_t"])
            & (proto["tmo_lvl"] == proto["cpl"])
        )
        stale = (proto["tmo_t"] > 0) & (t >= proto["tmo_t"])
        proto["tmo_t"] = jnp.where(stale, 0, proto["tmo_t"])

        send = (descended & ~proto["done"]) | tmo
        send = send & (proto["cursor"] < self._bs(proto["cpl"]))
        proto, em = self._send_swaps(state, send, proto)
        state = state._replace(proto=proto)
        return net.apply_emission(state, em)

    def all_done(self, state):
        return jnp.all(state.proto["done"])


def make_sanfermin_cappos(
    params: Optional[SanFerminParameters] = None,
    capacity: int = 1 << 14,
    seed: int = 0,
):
    params = params or SanFerminParameters()
    oracle = SanFerminCappos(params)
    oracle.init()
    latency = registry_network_latencies.get_by_name(params.network_latency_name)
    city_index = getattr(latency, "city_index", None)
    cols = build_node_columns(oracle.network().all_nodes, city_index)
    proto = BatchedSanFerminCappos(params)
    net = BatchedNetwork(proto, latency, params.node_count, capacity=capacity)
    state = net.init_state(
        cols, seed=seed, proto=proto.proto_init(params.node_count, seed=seed)
    )
    return net, state

"""OptimisticP2PSignature: the simplest signature exchange — flood every
signature over the P2P graph, finish at threshold (aggregation checked
optimistically at the end).

Reference semantics: protocols/OptimisticP2PSignature.java (SendSig message
:86-103, node flood-on-first-sight :114-133, init registers a self-sig task
per node at t=1 :156-165).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

from ..core.params import WParameters, register_protocol
from ..core.registries import registry_network_latencies, registry_node_builders
from ..oracle.messages import Message
from ..oracle.network import Network, Protocol
from ..oracle.p2p import P2PNetwork, P2PNode


@dataclasses.dataclass
class OptimisticP2PSignatureParameters(WParameters):
    node_count: int = 100
    threshold: int = 99
    connection_count: int = 20
    pairing_time: int = 1
    node_builder_name: Optional[str] = None
    network_latency_name: Optional[str] = None


class SendSig(Message):
    def __init__(self, who: "P2PSigNode"):
        self.sig = who.node_id

    def size(self) -> int:
        return 4 + 48  # NodeId + sig

    def action(self, network, from_node, to_node):
        to_node.on_sig(from_node, self)


class P2PSigNode(P2PNode):
    __slots__ = ("verified_signatures", "done", "_p")

    def __init__(self, p: "OptimisticP2PSignature"):
        super().__init__(p.network().rd, p.nb)
        self.verified_signatures = 0  # int-as-bitset
        self.done = False
        self._p = p

    def on_sig(self, from_node: "P2PSigNode", ss: SendSig) -> None:
        """Forward each unseen sig to all peers but the sender; finish at
        threshold with a 2*pairingTime verification delay
        (OptimisticP2PSignature.java:114-133)."""
        params, net = self._p.params, self._p.network()
        if not self.done and not (self.verified_signatures >> ss.sig) & 1:
            self.verified_signatures |= 1 << ss.sig
            dests = [n for n in self.peers if n is not from_node]
            net.send(ss, net.time + 1, self, dests)
            if self.verified_signatures.bit_count() >= params.threshold:
                self.done = True
                self.done_at = net.time + params.pairing_time * 2

    def __repr__(self) -> str:
        return (
            f"P2PSigNode{{nodeId={self.node_id}, doneAt={self.done_at}, "
            f"sigs={self.verified_signatures.bit_count()}, msgReceived={self.msg_received}, "
            f"msgSent={self.msg_sent}, KBytesSent={self.bytes_sent // 1024}, "
            f"KBytesReceived={self.bytes_received // 1024}}}"
        )


@register_protocol("OptimisticP2PSignature", OptimisticP2PSignatureParameters)
class OptimisticP2PSignature(Protocol):
    def __init__(self, params: OptimisticP2PSignatureParameters):
        self.params = params
        self._network: P2PNetwork[P2PSigNode] = P2PNetwork(params.connection_count, False)
        self.nb = registry_node_builders.get_by_name(params.node_builder_name)
        self._network.set_network_latency(
            registry_network_latencies.get_by_name(params.network_latency_name)
        )

    def copy(self) -> "OptimisticP2PSignature":
        return OptimisticP2PSignature(self.params)

    def init(self) -> None:
        for _ in range(self.params.node_count):
            n = P2PSigNode(self)
            self._network.add_node(n)
            self._network.register_task(
                (lambda nn: lambda: nn.on_sig(nn, SendSig(nn)))(n), 1, n
            )
        self._network.set_peers()

    def network(self) -> Network:
        return self._network


def main():
    nb = None
    nl = "NetworkLatencyByDistanceWJitter"
    p2ps = OptimisticP2PSignature(
        OptimisticP2PSignatureParameters(1000, 1000 // 2 + 1, 13, 3, nb, nl)
    )
    p2ps.init()
    observer = p2ps.network().get_node_by_id(0)
    p2ps.network().run(5)
    print(observer)


if __name__ == "__main__":
    main()

"""Batched P2PHandel: Handel-style aggregation over a generic P2P graph —
periodic push of missing-signature sets to the neighbour with the largest
diff.

Reference semantics: protocols/P2PHandel.java (node logic :255-480, init
tasks :482-509) via the oracle port `protocols/p2phandel.py`.

TPU-first design:

  * signature sets are dense bool matrices: `verified[N, N]`,
    `pend[N, N]` (the to_verify pool, pre-aggregated), and the per-peer
    knowledge cube `peers_state[N, P, N]` (P = max degree);
  * the periodic sendSigs beat picks argmax over per-peer diff
    cardinalities ([N, P] popcounts) and ships the diff bitset AS the
    message payload (PAYLOAD_WIDTH = N/32 words);
  * checkSigs2 (the default double-aggregate strategy,
    P2PHandel.java:455-479): the pending pool is a single OR-aggregate,
    verified once per free verification register.  The oracle can
    overlap two scheduled updates (it re-checks every pairingTime while
    an update is in flight for 2*pairingTime); here a new verification
    starts only when the register is free — worst case one extra
    pairingTime of latency per batch, documented;
  * checkSigs1 (double_aggregate_strategy=False, :419-447): the
    to_verify pool is CAND_K distinct candidate bitsets [N, K, N]; the
    beat prunes zero-value entries and verifies the one adding the most
    signatures.  Same single-register policy as checkSigs2; same-ms
    arrivals for one receiver merge into one pool entry (the oracle
    keeps them distinct — single-arrival ms, the common case at the
    default sigsSendPeriod, is exact);
  * State broadcasts (send_state=True, :305-317 + init :497-501): every
    node broadcasts its verified set to all peers at t=1 and on every
    improving non-final commit; receivers fold it into peers_state only
    (on_peer_state, :281-283).

Engine-limit approximations: per-message wire sizes are dynamic in the
reference (diff cardinality / range compression, :160-229) but the
engine's traffic counters are per-type static — byte counters here use
size 1 per SendSigs/State, so bytes stats are NOT comparable to the
oracle (message counts are).  On the wire, "dif" ships the diff and all
three other strategies ship the full verified set, exactly like the
oracle's _create_send_sigs (:389-404) — the compressed variants only
change the byte-size model, which is not modeled here.
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
import numpy as np

from ..core.node import build_node_columns
from ..core.registries import registry_network_latencies
from ..engine import BatchedNetwork, BatchedProtocol, Emission
from .p2pflood_batched import build_adjacency
from .p2phandel import P2PHandel, P2PHandelParameters


class BatchedP2PHandel(BatchedProtocol):
    MSG_TYPES = ["SEND_SIGS", "STATE"]
    TICK_INTERVAL = 1  # periodic beat + conditional checkSigs per ms
    CAND_K = 8  # checkSigs1 to_verify pool depth
    # ver_card cache (the PR-8 score-caching lever, p2phandel half):
    # `verified` changes only in tick's commit, and the merged cardinality
    # obeys |verified ∪ ver_sig| = |verified| + |ver_sig \ verified| — so
    # one carried int32[N] column replaces the two [N, N] bool reductions
    # per tick.  End-of-tick invariant (simlint SL701): ver_card equals
    # sum(verified, axis=1).  (peers_state cardinalities are NOT cacheable
    # this way: the delivery scatter-max can hit duplicate (to, slot)
    # destinations, which breaks the incremental identity.)
    SCORE_CACHE = True
    CACHE_LEAF_NAMES = ("ver_card",)

    def __init__(self, params: P2PHandelParameters, adjacency: np.ndarray, just_relay):
        self.params = params
        self.adj = jnp.asarray(adjacency, jnp.int32)
        self.n_nodes = params.signing_node_count + params.relaying_node_count
        self.just_relay = jnp.asarray(just_relay)
        self.PAYLOAD_WIDTH = (self.n_nodes + 31) // 32
        self.DERIVED_CACHE_LEAVES = (
            self.CACHE_LEAF_NAMES if self.SCORE_CACHE else ()
        )
        self.NARROW_LEAVES = self._narrow_plan()

    def _narrow_plan(self) -> tuple:
        """Density plan (engine.density, docs/density.md): ver_card is a
        verified-signature cardinality, provably <= N; carried narrow,
        computed in int32 inside the widen/narrow hook boundary.  Inert
        when SCORE_CACHE is off (the leaf is absent)."""
        from ..engine.density import NarrowLeaf, narrowest_int

        dt = narrowest_int(self.n_nodes)
        if dt.itemsize >= 4:
            return ()
        return (NarrowLeaf("ver_card", dt.name, self.n_nodes),)

    def msg_size(self, mtype: int) -> int:
        return 1  # dynamic in the reference; see the module docstring

    def _pack(self, bits):
        """bool[..., N] -> uint32 words [..., W] as int32 payload."""
        n = self.n_nodes
        pad = (-n) % 32
        b = jnp.pad(bits, [(0, 0)] * (bits.ndim - 1) + [(0, pad)])
        b = b.reshape(b.shape[:-1] + (self.PAYLOAD_WIDTH, 32))
        weights = (jnp.uint32(1) << jnp.arange(32, dtype=jnp.uint32)).astype(jnp.uint32)
        return jnp.sum(b.astype(jnp.uint32) * weights, axis=-1).astype(jnp.int32)

    def _unpack(self, words):
        """int32 words [..., W] -> bool[..., N]."""
        w = words.astype(jnp.uint32)
        bits = (w[..., None] >> jnp.arange(32, dtype=jnp.uint32)) & jnp.uint32(1)
        bits = bits.reshape(words.shape[:-1] + (self.PAYLOAD_WIDTH * 32,))
        return bits[..., : self.n_nodes] == 1

    def proto_init(self, n_nodes: int):
        n = self.n_nodes
        verified = jnp.zeros((n, n), bool)
        # signing nodes hold their own signature (ctor, :264-266)
        ids = jnp.arange(n)
        verified = verified.at[ids, ids].set(~self.just_relay)
        proto = {
            "verified": verified,
            "pend": jnp.zeros((n, n), bool),
            "peers_state": jnp.zeros((n, self.adj.shape[1], n), bool),
            "ver_active": jnp.zeros(n, bool),
            "ver_done_t": jnp.zeros(n, jnp.int32),
            "ver_sig": jnp.zeros((n, n), bool),
            "last_check": jnp.zeros(n, jnp.int32),
        }
        if not self.params.double_aggregate_strategy:
            proto["cand"] = jnp.zeros((n, self.CAND_K, n), bool)
        if self.SCORE_CACHE:
            proto["ver_card"] = jnp.sum(verified, axis=1)
        return self.narrow_proto(proto)

    def recompute_caches(self, state) -> dict:
        if not self.SCORE_CACHE:
            return {}
        # re-narrowed so the returned leaf matches the carried storage
        # dtype exactly (SL701 / checkpoint templates are dtype-strict)
        return self.narrow_proto(
            {"ver_card": jnp.sum(state.proto["verified"], axis=-1)}
        )

    def initial_emissions(self, net, state):
        if not self.params.send_state:
            return []
        # init registers sendStateToPeers at t=1 for every node (:497-501)
        n, n_peers = self.n_nodes, self.adj.shape[1]
        ids = jnp.arange(n, dtype=jnp.int32)
        return [
            Emission(
                mask=(self.adj >= 0).reshape(-1),
                from_idx=jnp.repeat(ids, n_peers),
                to_idx=jnp.maximum(self.adj, 0).reshape(-1),
                mtype=self.mtype("STATE"),
                payload=jnp.repeat(
                    self._pack(state.proto["verified"]), n_peers, axis=0
                ).reshape(n * n_peers, -1),
                send_time=jnp.int32(1),
            )
        ]

    # -- message handling ----------------------------------------------------
    def deliver(self, net, state, deliver_mask):
        # NARROW_LEAVES boundary (engine.density): hook bodies compute on
        # the int32 view, carried state stores the declared narrow dtypes
        state = state._replace(proto=self.widen_proto(state.proto))
        state, ems = self._deliver_impl(net, state, deliver_mask)
        return state._replace(proto=self.narrow_proto(state.proto)), ems

    def _deliver_impl(self, net, state, deliver_mask):
        proto = dict(state.proto)
        n = self.n_nodes
        to, frm = state.msg_to, state.msg_from
        sigs = self._unpack(state.msg_payload)  # [C, N]
        sigs = sigs & deliver_mask[:, None]
        is_ss = deliver_mask & (state.msg_type == self.mtype("SEND_SIGS"))

        # peers_state[to, slot(frm)] |= sigs — both SendSigs (onNewSig,
        # :330-334) and State (onPeerState, :281-283) fold in here
        slot_of = jnp.argmax(self.adj[to] == frm[:, None], axis=1)
        ok = jnp.take_along_axis(self.adj[to], slot_of[:, None], axis=1)[:, 0] == frm
        w_to = jnp.where(deliver_mask & ok, to, n)
        proto["peers_state"] = proto["peers_state"].at[w_to, slot_of].max(
            sigs, mode="drop"
        )
        ss_to = jnp.where(is_ss & ok, to, n)
        if self.params.double_aggregate_strategy:
            # checkSigs2 pool: one OR-aggregate
            proto["pend"] = proto["pend"].at[ss_to].max(sigs, mode="drop")
        else:
            # checkSigs1 pool: same-ms arrivals merge into ONE new entry,
            # which replaces the least-valuable slot if it adds more
            arrivals = jnp.zeros((n, n), bool).at[ss_to].max(sigs, mode="drop")
            has_new = jnp.any(arrivals, axis=1)
            cand = proto["cand"]
            verified = proto["verified"]
            v_k = jnp.sum(cand & ~verified[:, None, :], axis=2)  # [N, K]
            worst = jnp.argmin(v_k, axis=1)
            v_min = jnp.take_along_axis(v_k, worst[:, None], axis=1)[:, 0]
            v_new = jnp.sum(arrivals & ~verified, axis=1)
            insert = has_new & (v_new > v_min)
            proto["cand"] = cand.at[
                jnp.where(insert, jnp.arange(n, dtype=jnp.int32), n), worst
            ].set(arrivals, mode="drop")
        return state._replace(proto=proto), []

    # -- per-tick ------------------------------------------------------------
    def tick(self, net, state):
        state = state._replace(proto=self.widen_proto(state.proto))
        state = self._tick_impl(net, state)
        return state._replace(proto=self.narrow_proto(state.proto))

    def _tick_impl(self, net, state):
        p = self.params
        proto = dict(state.proto)
        n = self.n_nodes
        t = state.time
        ids = jnp.arange(n, dtype=jnp.int32)
        verified = proto["verified"]
        ps = proto["peers_state"]

        # 1. commit due verifications (updateVerifiedSignatures, :290-303)
        due = proto["ver_active"] & (t >= proto["ver_done_t"])
        if self.SCORE_CACHE:
            # carried cardinality + the union identity — one [N, N]
            # reduction (the delta) instead of two full recounts
            old_card = proto["ver_card"]
            delta = jnp.sum(proto["ver_sig"] & ~verified, axis=1)
            verified = jnp.where(
                due[:, None], verified | proto["ver_sig"], verified
            )
            new_card = jnp.where(due, old_card + delta, old_card)
            proto["ver_card"] = new_card
        else:
            old_card = jnp.sum(verified, axis=1)
            verified = jnp.where(
                due[:, None], verified | proto["ver_sig"], verified
            )
            new_card = jnp.sum(verified, axis=1)
        grew = due & (new_card > old_card)
        was_undone = state.done_at == 0
        reach = grew & was_undone & (new_card >= p.threshold)
        state = state._replace(done_at=jnp.where(reach, t, state.done_at))
        proto["ver_active"] = proto["ver_active"] & ~due

        # final aggregation to peers still short of threshold (:305-317)
        ps_card = jnp.sum(ps, axis=2)  # [N, P]
        needy = (ps_card < p.threshold) & (self.adj >= 0)
        fin = reach[:, None] & needy
        ps = jnp.where(fin[:, :, None], ps | verified[:, None, :], ps)
        n_peers = self.adj.shape[1]
        em_final = Emission(
            mask=fin.reshape(-1),
            from_idx=jnp.repeat(ids, n_peers),
            to_idx=jnp.maximum(self.adj, 0).reshape(-1),
            mtype=self.mtype("SEND_SIGS"),
            payload=jnp.repeat(
                self._pack(verified), n_peers, axis=0
            ).reshape(n * n_peers, -1),
        )
        em_state = None
        if p.send_state:
            # improving, non-final commit: broadcast State to all peers
            # (updateVerifiedSignatures elif branch, :299-301)
            st = grew & was_undone & ~reach
            em_state = Emission(
                mask=(st[:, None] & (self.adj >= 0)).reshape(-1),
                from_idx=jnp.repeat(ids, n_peers),
                to_idx=jnp.maximum(self.adj, 0).reshape(-1),
                mtype=self.mtype("STATE"),
                payload=jnp.repeat(
                    self._pack(verified), n_peers, axis=0
                ).reshape(n * n_peers, -1),
            )

        # 2. checkSigs beat: conditional task, min gap pairingTime
        # (init :505-509), single verification register (see header).
        # Known approximation: this reads same-tick state (arrivals of t,
        # phase-1 commits) where the reference's boundary-fired conditional
        # task sees end-of-(t-1) — a 1-tick information lead per
        # verification hop (handel/gsf _select got the boundary-view fix
        # in r5; here cand is [N, K, N]-dense and double-buffering it
        # costs more memory than the lead is worth at current parity)
        if p.double_aggregate_strategy:
            # checkSigs2 (:455-479): aggregate everything, verify once
            has_pend = jnp.any(proto["pend"], axis=1)
            check = (
                has_pend
                & (state.done_at == 0)
                & ~proto["ver_active"]
                & (t >= 1)
                & (t - proto["last_check"] >= p.pairing_time)
            )
            agg = proto["pend"]
            useful = jnp.any(agg & ~verified, axis=1) & check
            proto["pend"] = jnp.where(check[:, None], False, proto["pend"])
            chosen = agg
        else:
            # checkSigs1 (:419-447): prune zero-value entries, verify the
            # single best
            cand = proto["cand"]
            v_k = jnp.sum(cand & ~verified[:, None, :], axis=2)  # [N, K]
            occupied = jnp.any(cand, axis=2)
            cand = cand & (v_k > 0)[:, :, None]  # iterator discard
            check = (
                jnp.any(occupied, axis=1)
                & (state.done_at == 0)
                & ~proto["ver_active"]
                & (t >= 1)
                & (t - proto["last_check"] >= p.pairing_time)
            )
            best = jnp.argmax(v_k, axis=1)
            best_v = jnp.take_along_axis(v_k, best[:, None], axis=1)[:, 0]
            useful = check & (best_v > 0)
            chosen = jnp.take_along_axis(cand, best[:, None, None], axis=1)[:, 0]
            proto["cand"] = cand.at[
                jnp.where(useful, ids, n), best
            ].set(False, mode="drop")
        proto["last_check"] = jnp.where(check, t, proto["last_check"])
        proto["ver_active"] = proto["ver_active"] | useful
        proto["ver_done_t"] = jnp.where(
            useful, t + 2 * p.pairing_time, proto["ver_done_t"]
        )
        proto["ver_sig"] = jnp.where(useful[:, None], chosen, proto["ver_sig"])

        # 3. periodic sendSigs: push the largest diff (:336-354)
        beat = (t >= 1) & (
            jnp.equal((t - 1) % jnp.int32(p.sigs_send_period), 0)
        ) & (state.done_at == 0) & ~state.down
        diff = verified[:, None, :] & ~ps  # [N, P, N]
        dsz = jnp.sum(diff & (self.adj >= 0)[:, :, None], axis=2)
        best = jnp.argmax(dsz, axis=1)
        best_sz = jnp.take_along_axis(dsz, best[:, None], axis=1)[:, 0]
        send = beat & (best_sz > 0)
        dest = jnp.take_along_axis(self.adj, best[:, None], axis=1)[:, 0]
        to_send = jnp.take_along_axis(diff, best[:, None, None], axis=1)[:, 0]
        if p.strategy.value != "dif":
            # all / cmp_all / cmp_diff all ship the FULL verified set —
            # only their byte-size models differ (:389-404); the diff goes
            # on the wire for plain "dif" only
            to_send = verified
        w_n = jnp.where(send, ids, n)
        ps = ps.at[w_n, best].max(verified, mode="drop")
        em_push = Emission(
            mask=send,
            from_idx=ids,
            to_idx=jnp.maximum(dest, 0),
            mtype=self.mtype("SEND_SIGS"),
            payload=self._pack(to_send),
        )

        proto["verified"] = verified
        proto["peers_state"] = ps
        state = state._replace(proto=proto)
        state = net.apply_emission(state, em_push)
        state = net.apply_emission(state, em_final)
        if em_state is not None:
            state = net.apply_emission(state, em_state)
        return state

    def all_done(self, state):
        return jnp.all(jnp.where(~state.down, state.done_at > 0, True))


def make_p2phandel(
    params: Optional[P2PHandelParameters] = None,
    capacity: int = 1 << 13,
    seed: int = 0,
    score_cache: bool = True,
):
    """Host-side construction: oracle init builds the graph and the relay
    set (same JavaRandom stream).  `score_cache=False` disables the
    carried ver_card cardinality (ablation / bit-identity testing)."""
    params = params or P2PHandelParameters()
    oracle = P2PHandel(params)
    oracle.init()
    net_o = oracle.network()
    adj = build_adjacency(net_o)
    just_relay = np.array([nd.just_relay for nd in net_o.all_nodes])
    latency = registry_network_latencies.get_by_name(params.network_latency_name)
    city_index = getattr(latency, "city_index", None)
    cols = build_node_columns(net_o.all_nodes, city_index)
    proto = BatchedP2PHandel(params, adj, just_relay)
    proto.SCORE_CACHE = bool(score_cache)
    proto.DERIVED_CACHE_LEAVES = (
        proto.CACHE_LEAF_NAMES if score_cache else ()
    )
    net = BatchedNetwork(proto, latency, proto.n_nodes, capacity=capacity)
    state = net.init_state(cols, seed=seed, proto=proto.proto_init(proto.n_nodes))
    return net, state

"""Batched OptimisticP2PSignature: every node's signature floods the P2P
graph; a node finishes when it holds `threshold` distinct signatures.

Reference semantics: protocols/OptimisticP2PSignature.java — SendSig
(:86-103, 52 bytes), flood-on-first-sight with a done-stops-everything
guard (:114-133), the t=1 self-sig task per node (:156-165), and the
2*pairingTime verification delay on doneAt (:131).

Design: the same frontier reduction as p2pflood_batched, with the sig
bitset as a dense bool matrix `received[N, N]` (node × signature).  The
oracle's int-as-bitset popcount becomes a row-sum; the "done" guard
freezes a node's row (done nodes neither record nor forward new sigs —
OptimisticP2PSignature.java:117)."""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
import numpy as np

from ..core.node import build_node_columns
from ..core.registries import registry_network_latencies
from ..engine import BatchedNetwork, BatchedProtocol, Emission
from .optimistic_p2p_signature import (
    OptimisticP2PSignature,
    OptimisticP2PSignatureParameters,
)
from .p2pflood_batched import build_adjacency


class BatchedOptimisticP2PSignature(BatchedProtocol):
    MSG_TYPES = ["SEND_SIG"]
    PAYLOAD_WIDTH = 1  # signature id (the signer's node id)
    TICK_INTERVAL = None  # pure message protocol: engine may skip empty ms

    def __init__(self, params: OptimisticP2PSignatureParameters, adjacency: np.ndarray):
        self.params = params
        self.adj = jnp.asarray(adjacency, jnp.int32)
        self.n_nodes = params.node_count

    def msg_size(self, mtype: int) -> int:
        return 4 + 48  # NodeId + sig (OptimisticP2PSignature.java:92)

    def proto_init(self, n_nodes: int):
        # each node's own sig is recorded when its t=1 task runs on_sig on
        # itself; baked in here, with the forward as the initial emission
        return {"received": jnp.eye(n_nodes, dtype=bool)}

    def _forward(self, state, src, sig, mask, exclude):
        """src[K] forwards signature sig[K] to every peer except exclude[K]
        at time+1 (the `network.time + 1` send in on_sig)."""
        n_peers = self.adj.shape[1]
        src_r = jnp.repeat(src, n_peers)
        dest = self.adj[src].reshape(-1)
        ok = jnp.repeat(mask, n_peers) & (dest >= 0) & (dest != jnp.repeat(exclude, n_peers))
        return Emission(
            mask=ok,
            from_idx=src_r,
            to_idx=jnp.maximum(dest, 0),
            mtype=self.mtype("SEND_SIG"),
            payload=jnp.repeat(sig, n_peers)[:, None],
            send_time=jnp.broadcast_to(state.time + 1, ok.shape),
        )

    def initial_emissions(self, net, state):
        """The per-node registered task fires at t=1 and sends at t=2
        (OptimisticP2PSignature.java:156-165: `send(ss, time+1, ...)`)."""
        ids = jnp.arange(self.n_nodes, dtype=jnp.int32)
        em = self._forward(
            state._replace(time=jnp.int32(1)),
            ids,
            ids,
            jnp.ones(self.n_nodes, bool),
            jnp.full(self.n_nodes, -1, jnp.int32),
        )
        return [em]

    def deliver(self, net, state, deliver_mask):
        p = self.params
        c = deliver_mask.shape[0]
        to = state.msg_to
        sig = state.msg_payload[:, 0]
        received = state.proto["received"]
        was_done = state.done_at > 0
        fresh = deliver_mask & ~received[to, sig] & ~was_done[to]

        slot = jnp.arange(c, dtype=jnp.int32)
        winner = jnp.full((self.n_nodes, self.n_nodes), c, jnp.int32)
        winner = winner.at[to, sig].min(jnp.where(fresh, slot, c), mode="drop")
        is_winner = fresh & (winner[to, sig] == slot)

        received = received.at[to, sig].max(fresh, mode="drop")
        count = jnp.sum(received, axis=1).astype(jnp.int32)
        done = (count >= p.threshold) & ~was_done & ~state.down
        # doneAt = now + 2*pairingTime (OptimisticP2PSignature.java:131)
        done_at = jnp.where(
            done, state.time + 2 * p.pairing_time, state.done_at
        )

        em = self._forward(state, to, sig, is_winner, state.msg_from)
        state = state._replace(proto={"received": received}, done_at=done_at)
        return state, [em]

    def all_done(self, state):
        return jnp.all(jnp.where(~state.down, state.done_at > 0, True))


def make_optimistic(
    params: Optional[OptimisticP2PSignatureParameters] = None,
    capacity: int = 1 << 15,
    seed: int = 0,
):
    """Host-side construction: oracle init builds the P2P graph (same
    JavaRandom stream → identical topology), baked into the engine."""
    params = params or OptimisticP2PSignatureParameters()
    oracle = OptimisticP2PSignature(params)
    oracle.init()
    net_o = oracle.network()
    adj = build_adjacency(net_o)
    latency = registry_network_latencies.get_by_name(params.network_latency_name)
    city_index = getattr(latency, "city_index", None)
    cols = build_node_columns(net_o.all_nodes, city_index)
    proto = BatchedOptimisticP2PSignature(params, adj)
    # flat mode: gossip-forward waves are send-synchronized like p2pflood —
    # a forwarding burst can land on one arrival tick, which would need
    # wheel rows as wide as the ring
    net = BatchedNetwork(
        proto, latency, params.node_count, capacity=capacity, wheel_rows=0
    )
    state = net.init_state(
        cols, seed=seed, proto=proto.proto_init(params.node_count)
    )
    return net, state

"""HandelEth2: the Handel protocol applied to Eth2 attestation aggregation.

Reference semantics: protocols/handeleth2/ — HandelEth2.java (protocol +
rank setup), HNode.java (aggregation processes, verification loop),
HLevel.java (per-level incoming/outgoing contribution logic),
Attestation.java / AggToVerify.java / SendAggregation.java (values).

Differences from plain Handel, per the reference's own javadoc
(HandelEth2.java:15-22): several aggregations run concurrently (a new one
every PERIOD_TIME=6000 ms, each living PERIOD_AGG_TIME=18000 ms) sharing
ONE verification core; an aggregation carries multiple values (one
attestation bitset per head hash); there is no threshold — the
aggregation just runs its window out; dissemination backs off
exponentially (powers of 3) as peers get contacted.

Faithful-port notes (quirks preserved on purpose):
  * HLevel.bestToVerify's `bestInside` is dead code in the reference (the
    window is computed but not applied — "todo: we're not respecting the
    window's limits", HLevel.java:300-330); the selection is by
    sizeIfMerged score with removals of blacklisted/non-improving
    entries.
  * HNode.verify's retry loop re-reads the same process when nothing is
    verifiable (lastVerified only moves on success, HNode.java:262-287),
    and schedules the update at time + pairingTime - 1 (the -1 keeps the
    update ahead of the next verify beat).
  * onNewAgg bumps the per-process reception rank but checks the NODE's
    rank array for overflow (HNode.java:338-341).
  * failedVerification exists but nothing sends bad signatures, so the
    window only ever grows (to its 128 cap) — HandelEth2Test.testRunSimple
    asserts exactly that.

Bitsets are Python ints, as in the other oracle ports.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional

from ..core.node import Node
from ..core.params import WParameters, register_protocol
from ..core.registries import registry_network_latencies, registry_node_builders
from ..oracle.messages import Message
from ..oracle.network import Network, Protocol
from ..utils.bitset import cardinality as _card
from ..utils.more_math import log2, round_pow2

INT_MAX = 2**31 - 1

PERIOD_TIME = 6000
PERIOD_AGG_TIME = PERIOD_TIME * 3


@dataclasses.dataclass
class HandelEth2Parameters(WParameters):
    node_count: int = 64
    pairing_time: int = 3
    level_wait_time: int = 100
    period_duration_ms: int = 50
    nodes_down: int = 0
    node_builder_name: Optional[str] = None
    network_latency_name: Optional[str] = None
    desynchronized_start: int = 0

    def __post_init__(self):
        if self.nodes_down >= self.node_count or self.nodes_down < 0:
            raise ValueError(f"nodeCount={self.node_count}")
        if self.node_count.bit_count() != 1:
            raise ValueError("We support only power of two nodes in this simulation")


class Attestation:
    """An attestation is for a given height and a given block hash
    (Attestation.java)."""

    __slots__ = ("height", "hash", "who")

    def __init__(self, height: int, hash_: int, who):
        self.height = height
        self.hash = hash_
        if isinstance(who, int) and who >= 0:
            self.who = 1 << who
        else:
            raise TypeError(who)

    @classmethod
    def copy_of(cls, base: "Attestation", who_to_copy: int) -> "Attestation":
        a = cls.__new__(cls)
        a.height = base.height
        a.hash = base.hash
        a.who = who_to_copy
        return a

    def __repr__(self) -> str:
        return f"{{height={self.height}, hash={self.hash}, who={self.who:b}}}"


class AggToVerify:
    """(AggToVerify.java)."""

    __slots__ = ("from_id", "height", "own_hash", "rank", "attestations", "level")

    def __init__(self, from_id, level, own_hash, rank, attestations: List[Attestation]):
        if level <= 0 or from_id < 0 or own_hash < 0 or not attestations:
            raise ValueError()
        self.from_id = from_id
        self.own_hash = own_hash
        self.rank = rank
        self.attestations = attestations
        self.level = level
        self.height = attestations[0].height
        for a in attestations:
            if a.height != self.height:
                raise ValueError(f"bad attestation list:{attestations}")


class SendAggregation(Message):
    """The only message exchanged by the participants (SendAggregation.java)."""

    def __init__(self, level: int, own_hash: int, level_finished: bool, attestations):
        if isinstance(attestations, Attestation):
            attestations = [attestations]
        if not attestations:
            raise ValueError("attestations should not be empty")
        self.attestations = attestations
        self.height = attestations[0].height
        self.level = level
        self.own_hash = own_hash
        self.level_finished = level_finished
        found = False
        for a in attestations:
            if a.height != self.height:
                raise RuntimeError(f"bad height:{attestations}")
            if a.hash == own_hash:
                found = True
        if not found:
            raise RuntimeError("no attestation with your own hash?")

    def action(self, network, from_node, to_node) -> None:
        to_node.on_new_agg(from_node, self)


class HLevel:
    """One level of one aggregation process (HLevel.java)."""

    def __init__(
        self,
        node: "HNode",
        l0: Optional[Attestation] = None,
        previous: Optional["HLevel"] = None,
        peers: Optional[List["HNode"]] = None,
    ):
        self._node = node
        self.to_verify_agg: List[AggToVerify] = []
        self.outgoing_finished = False
        self.last_cardinality_sent = 0
        self.first_node_with_best_card = 0
        self.contacted_nodes = 0
        self.cycle_count = 0
        self.pos_in_level = 0
        if previous is None:
            # level 0: only our own signature (HLevel.java:44-57)
            self.level = 0
            self.peers: List["HNode"] = []
            self.peers_count = 1
            self.incoming_cardinality = 1
            self.outgoing_cardinality = 0
            self.incoming: Dict[int, Attestation] = {l0.hash: l0}
            self.outgoing: Dict[int, Attestation] = {}
            self.outgoing_finished = True
            self.ind_incoming: Dict[int, int] = {l0.hash: 1 << node.node_id}
        else:
            self.level = previous.level + 1
            self.peers_count = 1 << (self.level - 1)
            self.peers = peers
            if len(peers) != self.peers_count:
                raise RuntimeError(
                    f"size={self.peers_count}, peers.size()={len(peers)}"
                )
            self.incoming = {}
            self.outgoing = {}
            self.ind_incoming = {}
            self.incoming_cardinality = 0
            self.outgoing_cardinality = 0

    def do_cycle(self, own_hash: int, finished_peers: int, agg_start_time: int) -> None:
        if not self.is_open(agg_start_time):
            return
        self.cycle_count += 1
        if self._active_cycle():
            self._send(own_hash, finished_peers, 1)

    def _active_cycle(self) -> bool:
        """Exponential dissemination back-off (HLevel.java:85-88)."""
        m = self.contacted_nodes // self._node.handel_eth2.level_count()
        return (self.cycle_count % (3**m)) == 0

    def fast_path(self, own_hash: int, finished_peers: int) -> None:
        """Burst on completing a full contribution (HLevel.java:91-93)."""
        self._send(own_hash, finished_peers, self._node.handel_eth2.level_count())

    def _send(self, own_hash: int, finished_peers: int, dest_count: int) -> None:
        d = self.get_remaining_peers(finished_peers, dest_count)
        if not d:
            return
        sa = SendAggregation(
            self.level, own_hash, self.is_incoming_complete(), list(self.outgoing.values())
        )
        self._node.handel_eth2.network().send(sa, self._node, d)
        self.contacted_nodes += len(d)

    def is_open(self, agg_start_time: int) -> bool:
        """Level starts on timeout or once outgoing is complete
        (HLevel.java:106-117)."""
        if self.outgoing_finished:
            return False
        net = self._node.handel_eth2.network()
        if net.time - agg_start_time >= (self.level - 1) * self._node.handel_eth2.params.level_wait_time:
            return True
        return self.is_outgoing_complete()

    def get_remaining_peers(self, finished_peers: int, peers_ct: int) -> List["HNode"]:
        """(HLevel.java:123-157) incl. the already-sent loop detection."""
        res: List["HNode"] = []
        start = self.pos_in_level
        while peers_ct > 0 and not self.outgoing_finished:
            p = self.peers[self.pos_in_level]

            if (
                self.outgoing_cardinality == self.last_cardinality_sent
                and p.node_id == self.first_node_with_best_card
            ):
                # We looped: we've already sent this message to this node.
                return res

            self.pos_in_level += 1
            if self.pos_in_level >= len(self.peers):
                self.pos_in_level = 0

            if (
                not (finished_peers >> p.node_id) & 1
                and not (self._node.blacklist >> p.node_id) & 1
            ):
                res.append(p)
                peers_ct -= 1
            else:
                if self.pos_in_level == start:
                    self.outgoing_finished = True

        if self.outgoing_cardinality > self.last_cardinality_sent and res:
            self.first_node_with_best_card = res[0].node_id
            self.last_cardinality_sent = self.outgoing_cardinality
        return res

    def size_if_merged(self, sig: AggToVerify) -> int:
        """(HLevel.java:160-196)."""
        agg_map = dict(self.incoming)
        size = 0
        for av in sig.attestations:
            our = agg_map.pop(av.hash, None)
            if our is None:
                size += _card(av.who)
            elif not (our.who & av.who):
                size += _card(our.who) + _card(av.who)
            else:
                indivs = self.ind_incoming.get(our.hash)
                merged = av.who
                if indivs is not None:
                    merged = indivs | av.who
                size += max(_card(merged), _card(our.who))
        for our in agg_map.values():
            size += _card(our.who)
        if size > self.peers_count:
            raise RuntimeError(f"bad size: {size}, level={self}")
        return size

    @staticmethod
    def merge(e1: Dict[int, Attestation], e2: Dict[int, Attestation]) -> Dict[int, Attestation]:
        """Merge two non-overlapping contribution maps (HLevel.java:199-222)."""
        res: Dict[int, Attestation] = {}
        for k in set(e1) | set(e2):
            a1, a2 = e1.get(k), e2.get(k)
            if a1 is None:
                res[k] = a2
            elif a2 is None:
                res[k] = a1
            else:
                assert not (a1.who & a2.who)
                res[k] = Attestation.copy_of(a1, a1.who | a2.who)
        return res

    def merge_incoming(self, aggv: AggToVerify) -> None:
        """(HLevel.java:228-262)."""
        self.ind_incoming[aggv.own_hash] = self.ind_incoming.get(aggv.own_hash, 0) | (
            1 << aggv.from_id
        )

        for av in aggv.attestations:
            our = self.incoming.get(av.hash)
            if our is None:
                self.incoming[av.hash] = av
                self.incoming_cardinality += _card(av.who)
            elif not (our.who & av.who):
                self.incoming[av.hash] = Attestation.copy_of(our, our.who | av.who)
                self.incoming_cardinality += _card(av.who)
            else:
                indivs_h = self.ind_incoming.get(our.hash)
                merged = av.who
                if indivs_h is not None:
                    merged = indivs_h | av.who
                if _card(merged) > _card(our.who):
                    self.incoming_cardinality -= _card(our.who)
                    both = Attestation.copy_of(our, merged)
                    self.incoming[both.hash] = both
                    self.incoming_cardinality += _card(both.who)

        if self.incoming_cardinality > self.peers_count:
            raise RuntimeError(
                f"bad incomingCardinality: {self.incoming_cardinality}, level={self}"
            )

    def is_incoming_complete(self) -> bool:
        return self.incoming_cardinality == self.peers_count

    def is_outgoing_complete(self) -> bool:
        return self.outgoing_cardinality == self.peers_count

    def best_to_verify(self, curr_window_size: int, blacklist: int) -> Optional[AggToVerify]:
        """Scored selection with curation; the reference's window is
        computed but deliberately unused (HLevel.java:268-330)."""
        if curr_window_size < 1:
            raise RuntimeError()
        if not self.to_verify_agg:
            return None
        if self.is_incoming_complete():
            self.to_verify_agg.clear()
            return None

        window_index = self._node.handel_eth2.params.node_count
        best_outside: Optional[AggToVerify] = None
        best_inside: Optional[AggToVerify] = None
        best_score_outside = 0

        kept: List[AggToVerify] = []
        for atv in self.to_verify_agg:
            s = self.size_if_merged(atv)
            if (blacklist >> atv.from_id) & 1 or s <= self.incoming_cardinality:
                continue  # iterator remove
            kept.append(atv)
            if atv.rank < window_index:
                window_index = atv.rank
            if s > best_score_outside:
                best_score_outside = s
                best_outside = atv
        self.to_verify_agg[:] = kept

        if best_inside is not None:
            return best_inside
        return best_outside

    def __repr__(self) -> str:
        return (
            f"level:{self.level}, ic:{self.is_incoming_complete()}"
            f", oc:{self.is_outgoing_complete()}"
            f", is:{self.incoming_cardinality}, os:{self.outgoing_cardinality}"
        )


class HNode(Node):
    __slots__ = (
        "handel_eth2",
        "delta_start",
        "node_pairing_time",
        "agg_done",
        "contributions_total",
        "height",
        "peers_per_level",
        "reception_ranks",
        "running_aggs",
        "blacklist",
        "cur_windows_size",
        "last_verified",
    )

    def __init__(self, handel_eth2: "HandelEth2", delta_start: int, nb):
        super().__init__(handel_eth2.network().rd, nb, False)
        self.handel_eth2 = handel_eth2
        self.delta_start = delta_start
        self.node_pairing_time = int(max(1, handel_eth2.params.pairing_time * self.speed_ratio))
        self.agg_done = 0
        self.contributions_total = 0
        self.height = 1000
        self.peers_per_level: List[List["HNode"]] = []
        self.reception_ranks = [0] * handel_eth2.params.node_count
        self.running_aggs: Dict[int, "AggregationProcess"] = {}
        self.blacklist = 0
        self.cur_windows_size = 16
        self.last_verified: Optional["AggregationProcess"] = None

    def successful_verification(self) -> None:
        self.cur_windows_size = min(128, self.cur_windows_size * 2)

    def failed_verification(self) -> None:
        self.cur_windows_size = max(1, self.cur_windows_size // 4)

    def create(self, height: int) -> Attestation:
        """80% hash 0, 20%*80% hash 1, ... (HNode.java:62-73)."""
        h = 0
        while self.handel_eth2.network().rd.next_double() < 0.2:
            h += 1
        return Attestation(height, h, self.node_id)

    def peers_up_to_level(self, level: int) -> int:
        """(HNode.java:76-89)."""
        if level < 1:
            raise ValueError(f"round={level}")
        c_mask = (1 << level) - 1
        start = (c_mask | self.node_id) ^ c_mask
        end = self.node_id | c_mask
        end = min(end, self.handel_eth2.params.node_count - 1)
        res = ((1 << (end + 1)) - 1) ^ ((1 << start) - 1)
        res &= ~(1 << self.node_id)
        return res

    def communication_level(self, n: "HNode") -> int:
        """(HNode.java:92-108)."""
        if self.node_id == n.node_id:
            raise ValueError(f"same id: {n.node_id}")
        n1, n2 = self.node_id, n.node_id
        for l in range(1, self.handel_eth2.level_count() + 1):
            n1 >>= 1
            n2 >>= 1
            if n1 == n2:
                return l
        raise RuntimeError(f"Can't communicate with {n}")

    # -- the per-height process ---------------------------------------------
    def dissemination(self) -> None:
        for ap in self.running_aggs.values():
            ap.update_all_outgoing()
            for sfl in ap.levels:
                sfl.do_cycle(ap.own_hash, ap.finished_peers, ap.start_at)

    def verify(self) -> None:
        """One verification core shared by all processes (HNode.java:262-287)."""
        if not self.running_aggs:
            return
        if self.last_verified is None:
            self.last_verified = next(iter(self.running_aggs.values()))

        # the reference iterates runningAggs.size() times, but lastVerified
        # only moves on success, so every iteration resolves the SAME
        # process (HNode.java:268-287) — one scan is observably identical
        ap = self.running_aggs.get(self.last_verified.height + 1)
        if ap is None:
            ap = self.running_aggs[min(self.running_aggs.keys())]
        sa = ap.best_to_verify()
        if sa is not None:
            self.last_verified = ap
            tv = ap
            self.handel_eth2.network().register_task(
                lambda: tv.update_verified_signatures(sa),
                # -1: update before the verification loop runs again
                self.handel_eth2.network().time + self.node_pairing_time - 1,
                self,
            )

    def start_new_aggregation(self, base: Optional[Attestation] = None) -> None:
        if base is None:
            base = self.create(self.height + 1)
        self.height = base.height
        start_at = self.handel_eth2.network().time
        end_at = start_at + PERIOD_AGG_TIME
        ap = AggregationProcess(self, base, start_at, self.reception_ranks)
        if self.running_aggs.get(ap.height) is not None:
            raise RuntimeError()
        self.running_aggs[ap.height] = ap
        self.handel_eth2.network().register_task(
            lambda: self.stop_aggregation(base.height), end_at, self
        )

    def stop_aggregation(self, height: int) -> None:
        self.contributions_total += self.running_aggs[height].get_best_result_size()
        self.agg_done += 1
        del self.running_aggs[height]

    def on_new_agg(self, from_node: "HNode", agg: SendAggregation) -> None:
        """(HNode.java:317-349)."""
        ap = self.running_aggs.get(agg.height)
        if ap is None:
            return  # message received too early or too late

        if agg.level_finished:
            ap.finished_peers |= 1 << from_node.node_id

        hl = ap.levels[agg.level]

        rank = ap.reception_ranks[from_node.node_id]
        ap.reception_ranks[from_node.node_id] += self.handel_eth2.params.node_count
        # the reference checks the NODE's array here, not the process's
        if self.reception_ranks[from_node.node_id] <= 0:
            self.reception_ranks[from_node.node_id] = INT_MAX

        if not hl.is_incoming_complete():
            hl.to_verify_agg.append(
                AggToVerify(from_node.node_id, hl.level, agg.own_hash, rank, agg.attestations)
            )


class AggregationProcess:
    """An ongoing aggregation; Eth2 starts one every 6 s (HNode.java:111-258)."""

    __slots__ = (
        "_node",
        "height",
        "own_hash",
        "start_at",
        "end_at",
        "reception_ranks",
        "finished_peers",
        "levels",
        "last_level_verified",
    )

    def __init__(self, node: HNode, l0: Attestation, start_at: int, reception_ranks):
        self._node = node
        self.reception_ranks = list(reception_ranks)
        self.height = l0.height
        self.own_hash = l0.hash
        self.start_at = start_at
        # the reference stores startAt + PERIOD_TIME here (HNode.java:129)
        # even though the process actually lives PERIOD_AGG_TIME (the stop
        # task in startNewAggregation); unused in both, kept for parity
        self.end_at = start_at + PERIOD_TIME
        self.finished_peers = 0
        self.levels: List[HLevel] = []
        self.last_level_verified = 0
        self._init_level(node.handel_eth2.params.node_count, l0)
        assert len(self.levels) == node.handel_eth2.level_count() + 1

    def _init_level(self, node_count: int, l0: Attestation) -> None:
        rounded = round_pow2(node_count)
        last = HLevel(self._node, l0=l0)
        self.levels.append(last)
        l = 1
        while 2**l <= rounded:
            last = HLevel(self._node, previous=last, peers=self._node.peers_per_level[l])
            self.levels.append(last)
            l += 1

    def best_to_verify(self) -> Optional[AggToVerify]:
        """Level 1 first, then a cycling cursor (HNode.java:148-175)."""
        node = self._node
        res1 = self.levels[1].best_to_verify(node.cur_windows_size, node.blacklist)
        if res1 is not None:
            return res1

        start = self.last_level_verified
        for _ in range(2, len(self.levels) + 1):
            hl = self.levels[start]
            res = hl.best_to_verify(node.cur_windows_size, node.blacklist)
            if res is not None:
                self.last_level_verified = start
                return res
            start += 1
            if start >= len(self.levels):
                start = 2
        return None

    def update_verified_signatures(self, vs: AggToVerify) -> None:
        """(HNode.java:181-205)."""
        node = self._node
        hl = self.levels[vs.level]
        if vs.height != self.height:
            raise RuntimeError(f"wrong heights, vs:{vs}, ap={self}")
        if hl.is_incoming_complete():
            raise RuntimeError(
                f"No need to verify a contribution for a complete level. vs:{vs}"
            )

        hl.merge_incoming(vs)
        node.successful_verification()

        if hl.is_incoming_complete() and hl.level < node.handel_eth2.level_count():
            self.update_all_outgoing()
            # NOTE: the range excludes the top level (levels run 0..levelCount
            # but the bound is levelCount, exclusive) — the reference does
            # exactly this (HNode.java:195-203), so the widest level never
            # fast-paths; preserved bug-for-bug
            for l in range(hl.level + 1, node.handel_eth2.level_count()):
                hu = self.levels[l]
                if hu.is_outgoing_complete():
                    hu.fast_path(self.own_hash, self.finished_peers)

    def update_all_outgoing(self) -> None:
        """(HNode.java:208-231)."""
        atts: Dict[int, Attestation] = {}
        size = 0
        for hl in self.levels:
            if hl.is_open(self.start_at):
                hl.outgoing = dict(atts)
                hl.outgoing_cardinality = size
            for a in hl.incoming.values():
                existing = atts.get(a.hash)
                size += _card(a.who)
                if existing is None:
                    atts[a.hash] = a
                else:
                    atts[a.hash] = Attestation.copy_of(existing, existing.who | a.who)

    def get_best_result(self) -> Dict[int, Attestation]:
        last = self.levels[-1]
        return HLevel.merge(last.incoming, last.outgoing)

    def get_best_result_size(self) -> int:
        last = self.levels[-1]
        return last.incoming_cardinality + last.outgoing_cardinality


@register_protocol("HandelEth2", HandelEth2Parameters)
class HandelEth2(Protocol):
    def __init__(self, params: HandelEth2Parameters):
        self.params = params
        self._network: Network[HNode] = Network()
        self._network.set_network_latency(
            registry_network_latencies.get_by_name(params.network_latency_name)
        )

    def network(self) -> Network:
        return self._network

    def copy(self) -> "HandelEth2":
        return HandelEth2(self.params)

    def level_count(self) -> int:
        return log2(self.params.node_count)

    def init(self) -> None:
        p = self.params
        nb = registry_node_builders.get_by_name(p.node_builder_name)
        bad = Network.choose_bad_nodes(self._network.rd, p.node_count, p.nodes_down)

        for i in range(p.node_count):
            start_at = (
                0
                if p.desynchronized_start == 0
                else self._network.rd.next_int(p.desynchronized_start)
            )
            n = HNode(self, start_at, nb)
            if i in bad:
                n.stop()
            self._network.add_node(n)

        self._set_reception_ranks()
        self._set_emission_ranks()

        for n in self._network.all_nodes:
            if not n.is_down():
                self._network.register_periodic_task(
                    n.start_new_aggregation, n.delta_start + 1, PERIOD_TIME, n
                )
                self._network.register_periodic_task(
                    n.dissemination, n.delta_start + 1, p.period_duration_ms, n
                )
                self._network.register_periodic_task(
                    n.verify, n.delta_start + 1, n.node_pairing_time, n
                )

    def _set_reception_ranks(self) -> None:
        """(HandelEth2.java:87-95): one shared, repeatedly-shuffled list."""
        all_ = list(self._network.all_nodes)
        for s in self._network.all_nodes:
            self._network.rd.shuffle(all_)
            for i, e in enumerate(all_):
                s.reception_ranks[e.node_id] = i

    def _set_emission_ranks(self) -> None:
        """We speak first to the nodes that listen to us first
        (HandelEth2.java:103-147)."""
        p = self.params
        for sender in self._network.all_nodes:
            if sender.is_down():
                continue
            our_rank_in_dest: List[Optional[List[HNode]]] = [None] * p.node_count
            for receiver in self._network.all_nodes:
                rec_rank = receiver.reception_ranks[sender.node_id]
                if our_rank_in_dest[rec_rank] is None:
                    our_rank_in_dest[rec_rank] = []
                our_rank_in_dest[rec_rank].append(receiver)

            assert not sender.peers_per_level
            sender.peers_per_level.append([])  # level 0
            for _ in range(1, self.level_count() + 1):
                sender.peers_per_level.append([])

            for lr in our_rank_in_dest:
                if lr is None:
                    continue
                for n in lr:
                    if n is not sender:
                        com_level = sender.communication_level(n)
                        sender.peers_per_level[com_level].append(n)

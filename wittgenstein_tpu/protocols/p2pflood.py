"""P2PFlood: libp2p-style flood routing on a random P2P graph.

Reference semantics: protocols/P2PFlood.java — dead nodes stay in peer
lists but neither send nor receive (a byzantine-ish availability lie);
`msgCount` random live senders each flood one message; a node is done when
it has received `msgCount` distinct flood messages (P2PFlood.java:39-43).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

from ..core.params import WParameters, register_protocol
from ..core.registries import registry_network_latencies, registry_node_builders
from ..oracle.messages import FloodMessage
from ..oracle.network import Protocol
from ..oracle.p2p import P2PNetwork, P2PNode


@dataclasses.dataclass
class P2PFloodParameters(WParameters):
    node_count: int = 100
    dead_node_count: int = 10
    delay_before_resent: int = 50
    msg_count: int = 1
    msg_to_receive: int = 1
    peers_count: int = 10
    delay_between_sends: int = 30
    node_builder_name: Optional[str] = None
    network_latency_name: Optional[str] = None


class P2PFloodNode(P2PNode):
    __slots__ = ("_params", "_net")

    def __init__(self, network, nb, down: bool, params):
        super().__init__(network.rd, nb)
        self._params = params
        self._net = network
        if down:
            self.stop()

    def on_flood(self, from_node, flood_message) -> None:
        if len(self.get_msg_received(flood_message.msg_id())) == self._params.msg_count:
            self.done_at = self._net.time


@register_protocol("P2PFlood", P2PFloodParameters)
class P2PFlood(Protocol):
    def __init__(self, params: P2PFloodParameters):
        self.params = params
        self._network: P2PNetwork[P2PFloodNode] = P2PNetwork(params.peers_count, True)
        self.nb = registry_node_builders.get_by_name(params.node_builder_name)
        self._network.set_network_latency(
            registry_network_latencies.get_by_name(params.network_latency_name)
        )

    def __str__(self) -> str:
        p, net = self.params, self._network
        return (
            f"nodes={p.node_count}, deadNodes={p.dead_node_count}"
            f", delayBeforeResent={p.delay_before_resent}ms, msgSent={p.msg_count}"
            f", msgToReceive={p.msg_to_receive}, peers(minimum)={p.peers_count}"
            f", peers(avg)={net.avg_peers()}, delayBetweenSends={p.delay_between_sends}ms"
            f", latency={type(net.network_latency).__name__}"
        )

    def copy(self) -> "P2PFlood":
        return P2PFlood(self.params)

    def init(self) -> None:
        p = self.params
        for i in range(p.node_count):
            self._network.add_node(
                P2PFloodNode(self._network, self.nb, i < p.dead_node_count, p)
            )
        self._network.set_peers()

        senders: set = set()
        while len(senders) < p.msg_count:
            node_id = self._network.rd.next_int(p.node_count)
            from_node = self._network.get_node_by_id(node_id)
            if not from_node.is_down() and node_id not in senders:
                senders.add(node_id)
                m = FloodMessage(1, p.delay_before_resent, p.delay_between_sends)
                self._network.send_peers(m, from_node)
                if p.msg_count == 1:
                    from_node.done_at = 1

    def network(self) -> P2PNetwork:
        return self._network

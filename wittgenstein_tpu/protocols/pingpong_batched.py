"""Batched PingPong: the canonical first protocol on the TPU engine.

Same behavior as protocols/PingPong.java — a witness Pings everyone, each
node Pongs back, the witness counts pongs — expressed as two vectorized
message kernels instead of per-object callbacks."""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax.numpy as jnp
import numpy as np

from ..core.node import build_node_columns
from ..core.registries import registry_network_latencies, registry_node_builders
from ..engine import BatchedNetwork, BatchedProtocol, Emission
from ..utils.javarand import JavaRandom


class BatchedPingPong(BatchedProtocol):
    MSG_TYPES = ["PING", "PONG"]
    TICK_INTERVAL = None  # pure message protocol: engine may skip empty ms

    def __init__(self, n_nodes: int, witness: int = 0):
        self.n_nodes = n_nodes
        self.witness = witness

    def proto_init(self, n_nodes: int):
        return {"pong": jnp.zeros(n_nodes, dtype=jnp.int32)}

    def initial_emissions(self, net, state):
        # network.sendAll(new Ping(), witness) at t=0 -> sendTime 1
        n = self.n_nodes
        return [
            Emission(
                mask=jnp.ones(n, dtype=bool),
                from_idx=jnp.full(n, self.witness, dtype=jnp.int32),
                to_idx=jnp.arange(n, dtype=jnp.int32),
                mtype=self.mtype("PING"),
                send_time=jnp.int32(1),
            )
        ]

    def deliver(self, net, state, deliver_mask):
        ping = deliver_mask & (state.msg_type == self.mtype("PING"))
        pong = deliver_mask & (state.msg_type == self.mtype("PONG"))
        # on_ping: reply Pong to the sender (PingPong.java onPing)
        emissions = [
            Emission(
                mask=ping,
                from_idx=state.msg_to,
                to_idx=state.msg_from,
                mtype=self.mtype("PONG"),
            )
        ]
        # on_pong: count (commutative scatter-add)
        new_pong = state.proto["pong"].at[state.msg_to].add(
            pong.astype(jnp.int32), mode="drop"
        )
        return state._replace(proto={"pong": new_pong}), emissions

    def all_done(self, state):
        return state.proto["pong"][self.witness] >= self.n_nodes


def make_pingpong(
    node_ct: int = 1000,
    node_builder_name: Optional[str] = None,
    network_latency_name: Optional[str] = None,
    capacity: Optional[int] = None,
    seed: int = 0,
    wheel_rows: Optional[int] = None,
    telemetry=None,
):
    """Host-side construction mirroring PingPong.init(): build the node
    population with the same JavaRandom stream as the oracle, convert to SoA
    columns, return (net, state).  wheel_rows=0 selects the flat message
    store (the wheel-parity reference, see docs/engine_timewheel.md);
    telemetry takes a telemetry.TelemetryConfig (None = uninstrumented)."""
    nb = registry_node_builders.get_by_name(node_builder_name)
    latency = registry_network_latencies.get_by_name(network_latency_name)
    rd = JavaRandom(0)
    from ..core.node import Node

    nodes = [Node(rd, nb) for _ in range(node_ct)]
    city_index = getattr(latency, "city_index", None)
    cols = build_node_columns(nodes, city_index)
    proto = BatchedPingPong(node_ct)
    cap = capacity if capacity is not None else 2 * node_ct + 64
    net = BatchedNetwork(
        proto, latency, node_ct, capacity=cap, wheel_rows=wheel_rows,
        telemetry=telemetry,
    )
    state = net.init_state(cols, seed=seed, proto=proto.proto_init(node_ct))
    return net, state

"""GSFSignature: "Gossiping San Fermin" BLS signature aggregation.

Reference semantics: protocols/GSFSignature.java — per-node binary levels
(the allSigsAtLevel bitmask trick, :361-374), a periodic doCycle drumbeat
per level (:313-324), level timeouts level*timeoutPerLevelMs (:292),
accelerated calls on level completion (:438-451), signature scoring
evaluateSig (:478-520), and verification modeled as a conditional task
costing pairingTime per check (:630-631).

Bitsets are Python ints (or/and/andNot/cardinality are int ops).  One
Java-visible subtlety is preserved: a SendSigs object multicast to several
peers shares ONE sigs bitset, and updateVerifiedSignatures mutates it
(or-ing indivVerifiedSig / merging non-intersecting sets) before the point
where the Java code rebinds the local variable — so mutations must write
through to the message (`holder.sigs`) exactly until that rebind.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional

from ..core.node import Node
from ..core.params import WParameters, register_protocol
from ..core.registries import registry_network_latencies, registry_node_builders
from ..oracle.messages import Message
from ..oracle.network import Network, Protocol
from ..utils.bitset import cardinality as _card, include as _include, to_ids as _bits_to_ids
from ..utils.more_math import round_pow2


@dataclasses.dataclass
class GSFSignatureParameters(WParameters):
    node_count: int = 32768 // 32
    threshold: float = -1  # int count, or a (0,1] ratio; -1 = 99% default
    pairing_time: int = 3
    timeout_per_level_ms: int = 50
    period_duration_ms: int = 10
    accelerated_calls_count: int = 10
    nodes_down: float = 0  # int count or a [0,1) ratio
    node_builder_name: Optional[str] = None
    network_latency_name: Optional[str] = None

    def __post_init__(self):
        from ._aggregation import normalize_agg_params

        normalize_agg_params(self)


class SendSigs(Message):
    """Signature-set message (GSFSignature.java:143-164); `sigs` is shared
    mutable state across all receivers of one multicast."""

    def __init__(self, from_node: "GSFNode", sigs: int, level: "SFLevel"):
        self.sigs = sigs
        self.from_node = from_node
        self.level = level.level
        # Size = level byte + bit field + the aggregated sig + our own sig
        self._size = 1 + level.expected_sigs() // 8 + 96
        self.level_finished = level.verified_signatures == level.waited_sigs
        self.received = _card(level.verified_signatures)

    def size(self) -> int:
        return self._size

    def action(self, network, from_node, to_node) -> None:
        to_node.on_new_sig(from_node, self)


class SFLevel:
    """One San Fermin level (GSFSignature.java:236-358)."""

    def __init__(self, node: "GSFNode", previous: Optional["SFLevel"] = None, all_previous: int = 0):
        self._node = node
        if previous is None:
            self.level = 0
            self.waited_sigs = 1 << node.node_id
            self.verified_signatures = 1 << node.node_id
            self.peers: List["GSFNode"] = []
            self.remaining_calls = 0
        else:
            self.level = previous.level + 1
            self.waited_sigs = node.all_sigs_at_level(self.level) & ~all_previous
            self.verified_signatures = 0
            self.peers = node.random_subset(self.waited_sigs, 2**31 - 1)
            self.remaining_calls = len(self.peers)
        self.individual_signatures = 0
        self.indiv_verified_sig = 0
        self.received: Dict["GSFNode", int] = {}
        self.pos_in_level = 0

    def expected_sigs(self) -> int:
        return _card(self.waited_sigs)

    def has_started(self, to_send: int) -> bool:
        """Level starts on timeout or once we hold all it needs
        (GSFSignature.java:289-309)."""
        net = self._node.network_ref
        if net.time >= self.level * self._node.params.timeout_per_level_ms:
            return True
        if _card(to_send) >= self.expected_sigs():
            return True
        return False

    def do_cycle(self, to_send: int) -> None:
        if self.remaining_calls == 0 or not self.has_started(to_send):
            return
        dest = self.get_remaining_peers(1)
        if dest:
            ss = SendSigs(self._node, to_send, self)
            self._node.network_ref.send(ss, self._node, dest[0])

    def get_remaining_peers(self, peers_ct: int) -> List["GSFNode"]:
        """Round-robin through the level's peer list; the reference's
        received-map filter is disabled by an `|| true` (GSFSignature.java:
        327-343), so every candidate is taken."""
        res: List["GSFNode"] = []
        while peers_ct > 0 and self.remaining_calls > 0:
            self.remaining_calls -= 1
            p = self.peers[self.pos_in_level]
            self.pos_in_level += 1
            if self.pos_in_level >= len(self.peers):
                self.pos_in_level = 0
            res.append(p)
            peers_ct -= 1
        return res

    def has_received_all(self) -> bool:
        wanted = self.waited_sigs & self.verified_signatures
        return _card(wanted) >= 0.8 * self.expected_sigs()


class GSFNode(Node):
    __slots__ = (
        "network_ref",
        "params",
        "to_verify",
        "levels",
        "verified_signatures",
        "node_pairing_time",
        "done",
        "sig_checked",
        "sig_queue_size",
    )

    def __init__(self, network: Network, nb, params: GSFSignatureParameters):
        super().__init__(network.rd, nb)
        self.network_ref = network
        self.params = params
        self.to_verify: List[SendSigs] = []
        self.levels: List[SFLevel] = []
        self.verified_signatures = 1 << self.node_id
        self.node_pairing_time = int(max(1, params.pairing_time * self.speed_ratio))
        self.done = False
        self.sig_checked = 0
        self.sig_queue_size = 0

    def init_level(self) -> None:
        rounded = round_pow2(self.params.node_count)
        all_previous = 0
        last = SFLevel(self)
        self.levels.append(last)
        l = 1
        while 2**l <= rounded:
            all_previous |= last.waited_sigs
            last = SFLevel(self, last, all_previous)
            self.levels.append(last)
            l += 1

    def get_last_finished_level(self) -> int:
        res = 0
        sfl = self.levels[0]
        while True:
            if sfl.waited_sigs == sfl.verified_signatures:
                res |= sfl.waited_sigs
                if sfl.level < len(self.levels) - 1:
                    sfl = self.levels[sfl.level + 1]
                else:
                    return res
            else:
                return res

    def do_cycle(self) -> None:
        to_send = self.get_last_finished_level()
        for sfl in self.levels:
            sfl.do_cycle(to_send)
            to_send |= sfl.verified_signatures

    def all_sigs_at_level(self, round_: int) -> int:
        """Binary-tree membership trick (GSFSignature.java:361-374)."""
        from ._aggregation import all_sigs_at_level

        return all_sigs_at_level(self.node_id, round_, self.params.node_count)

    def update_verified_signatures(self, from_node: "GSFNode", level: int, holder: SendSigs) -> None:
        """Merge a verified signature set (GSFSignature.java:379-460).
        Mutations write through holder.sigs until the Java code rebinds."""
        sfl = self.levels[level]

        if _card(holder.sigs) == 1:
            sfl.indiv_verified_sig |= 1 << from_node.node_id
        holder.sigs |= sfl.indiv_verified_sig
        sigs = holder.sigs
        rebound = False

        reset_remaining = False
        if _card(sigs) > sfl.expected_sigs():
            # sender included our lower levels too: absorb level by level
            i = 1
            while i < len(self.levels) and _include(sigs, self.levels[i].waited_sigs):
                lv = self.levels[i]
                if lv.verified_signatures != lv.waited_sigs:
                    lv.verified_signatures |= lv.waited_sigs
                    self.verified_signatures |= lv.waited_sigs
                    reset_remaining = True
                if reset_remaining:
                    lv.remaining_calls = len(lv.peers)
                i += 1
            sigs = sfl.waited_sigs
            rebound = True

        if _card(sfl.verified_signatures) > 0 and (sigs & sfl.verified_signatures) == 0:
            # disjoint sets aggregate
            sigs |= sfl.verified_signatures
            if not rebound:
                holder.sigs = sigs

        if _card(sigs) > _card(sfl.verified_signatures) or reset_remaining:
            for i in range(sfl.level, len(self.levels)):
                self.levels[i].remaining_calls = len(self.levels[i].peers)

            # replacement, not completion
            sfl.verified_signatures &= ~sfl.waited_sigs
            sfl.verified_signatures |= sigs
            self.verified_signatures &= ~sfl.waited_sigs
            self.verified_signatures |= sigs

            if self.params.accelerated_calls_count > 0:
                best_to_send = self.get_last_finished_level()
                while _include(best_to_send, sfl.waited_sigs) and sfl.level < len(self.levels) - 1:
                    sfl = self.levels[sfl.level + 1]
                    send_sigs = SendSigs(self, best_to_send, sfl)
                    peers = sfl.get_remaining_peers(self.params.accelerated_calls_count)
                    if peers:
                        self.network_ref.send(send_sigs, self, peers)
            if self.done_at == 0 and _card(self.verified_signatures) >= self.params.threshold:
                self.done_at = self.network_ref.time

    def random_subset(self, bits: int, node_ct: int) -> List["GSFNode"]:
        res = [self.network_ref.get_node_by_id(i) for i in _bits_to_ids(bits)]
        self.network_ref.rd.shuffle(res)
        return res[:node_ct] if len(res) > node_ct else res

    def evaluate_sig(self, l: SFLevel, sig: int) -> int:
        """Interest score of verifying `sig` (GSFSignature.java:478-520)."""
        if _card(l.verified_signatures) >= l.expected_sigs():
            return 0

        with_indiv = l.indiv_verified_sig | sig

        if _card(l.verified_signatures) == 0:
            new_total = _card(sig)
            added_sigs = new_total
        elif sig & l.verified_signatures:
            new_total = _card(with_indiv)
            added_sigs = new_total - _card(l.verified_signatures)
        else:
            with_indiv |= l.verified_signatures
            new_total = _card(with_indiv)
            added_sigs = new_total - _card(l.verified_signatures)

        if added_sigs <= 0:
            if _card(sig) == 1 and not (sig & l.indiv_verified_sig):
                return 1
            return 0

        if new_total == l.expected_sigs():
            return 1000000 - l.level * 10
        return 100000 - l.level * 100 + added_sigs

    def on_new_sig(self, from_node: "GSFNode", ssigs: SendSigs) -> None:
        l = self.levels[ssigs.level]
        if ssigs.level_finished:
            l.received[from_node] = 1
        self.to_verify.append(ssigs)
        # individual sig tracked for byzantine resistance
        if not (l.individual_signatures >> from_node.node_id) & 1:
            si = SendSigs(from_node, 1 << from_node.node_id, l)
            self.to_verify.append(si)
            l.individual_signatures |= 1 << from_node.node_id
        self.sig_queue_size = len(self.to_verify)

    def check_sigs(self) -> None:
        best = None
        score = 0
        kept = []
        for cur in self.to_verify:
            l = self.levels[cur.level]
            ns = self.evaluate_sig(l, cur.sigs)
            if ns > score:
                score = ns
                best = cur
                kept.append(cur)
            elif ns == 0:
                continue  # drop worthless entries (iterator remove)
            else:
                kept.append(cur)
        self.to_verify = kept
        if best is not None:
            self.to_verify.remove(best)
            self.sig_checked += 1
            self.sig_queue_size = len(self.to_verify)
            t_best = best
            self.network_ref.register_task(
                lambda: self.update_verified_signatures(
                    t_best.from_node, t_best.level, t_best
                ),
                self.network_ref.time + self.node_pairing_time,
                self,
            )

    def __repr__(self) -> str:
        return (
            f"GSFNode{{nodeId={self.node_id}, doneAt={self.done_at}"
            f", sigs={_card(self.verified_signatures)}, msgReceived={self.msg_received}"
            f", msgSent={self.msg_sent}, KBytesSent={self.bytes_sent // 1024}"
            f", KBytesReceived={self.bytes_received // 1024}}}"
        )


@register_protocol("GSFSignature", GSFSignatureParameters)
class GSFSignature(Protocol):
    def __init__(self, params: GSFSignatureParameters):
        self.params = params
        self.nb = registry_node_builders.get_by_name(params.node_builder_name)
        self._network: Network[GSFNode] = Network()
        self._network.set_network_latency(
            registry_network_latencies.get_by_name(params.network_latency_name)
        )

    def __str__(self) -> str:
        p = self.params
        return (
            f"GSFSignature, nodes={p.node_count}, threshold={p.threshold}"
            f", pairing={p.pairing_time}ms, level waitTime={p.timeout_per_level_ms}ms"
            f", period={p.period_duration_ms}ms"
            f", acceleratedCallsCount={p.accelerated_calls_count}"
            f", dead nodes={p.nodes_down}, builder={p.node_builder_name}"
        )

    def copy(self) -> "GSFSignature":
        return GSFSignature(self.params)

    def init(self) -> None:
        p = self.params
        for _ in range(p.node_count):
            self._network.add_node(GSFNode(self._network, self.nb, p))

        set_down = 0
        while set_down < p.nodes_down:
            down = self._network.rd.next_int(p.node_count)
            n = self._network.all_nodes[down]
            if not n.is_down() and down != 1:
                # node 1 kept up to help debugging (GSFSignature.java:621)
                n.stop()
                set_down += 1

        for n in self._network.all_nodes:
            if not n.is_down():
                n.init_level()
                self._network.register_periodic_task(
                    n.do_cycle, 1, p.period_duration_ms, n
                )
                self._network.register_conditional_task(
                    n.check_sigs,
                    1,
                    n.node_pairing_time,
                    n,
                    lambda n=n: len(n.to_verify) > 0,
                    lambda n=n: not n.done,
                )

    def network(self) -> Network:
        return self._network

"""Shared pieces of the Avalanche family (Slush / Snowflake): query/answer
messages, the sampling node base, and the colored-node scenario driver.

Reference semantics: the Query/AnswerQuery/Answer inner classes and node
sampling loops are identical between protocols/Slush.java:86-220 and
protocols/Snowflake.java:95-232; only onAnswer's accounting differs (round/M
vs cnt/B), which stays in the concrete protocol modules.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from ..core import stats as SH
from ..core.node import Node
from ..core.runners import ProgressPerTime
from ..oracle.messages import Message

COLOR_NB = 2


class Query(Message):
    def __init__(self, id_: int, color: int):
        self.id = id_
        self.color = color

    def action(self, network, from_node, to_node):
        to_node.on_query(self, from_node)


class AnswerQuery(Message):
    def __init__(self, original_query: Query, color: int):
        self.original_query = original_query
        self.color = color

    def action(self, network, from_node, to_node):
        to_node.on_answer(self.original_query.id, self.color)


class Answer:
    __slots__ = ("round", "colors_found")

    def __init__(self, round_: int):
        self.round = round_
        self.colors_found = [0] * (COLOR_NB + 1)

    def answer_count(self) -> int:
        return sum(self.colors_found)


class AvalancheNode(Node):
    """Sampling node base: uncolored nodes adopt the first color they are
    queried with; every node answers with its current color; K distinct
    random remotes per query (Slush.java:126-154 == Snowflake.java:136-159).

    The concrete protocol provides on_answer()."""

    __slots__ = ("my_color", "my_query_nonce", "answer_ip", "_p")

    def __init__(self, p):
        super().__init__(p.network().rd, p.nb)
        self.my_color = 0
        self.my_query_nonce = 0
        self.answer_ip: Dict[int, Answer] = {}
        self._p = p

    def random_remotes(self) -> List["AvalancheNode"]:
        p, net = self._p, self._p.network()
        res: List[AvalancheNode] = []
        while len(res) != p.params.k:
            r = net.rd.next_int(p.params.nodes_av)
            if r != self.node_id and net.get_node_by_id(r) not in res:
                res.append(net.get_node_by_id(r))
        return res

    def _other_color(self) -> int:
        return 2 if self.my_color == 1 else 1

    def on_query(self, qa: Query, from_node: "AvalancheNode") -> None:
        if self.my_color == 0:
            self.my_color = qa.color
            self.send_query(1)
        self._p.network().send(AnswerQuery(qa, self.my_color), self, from_node)

    def on_answer(self, query_id: int, color: int) -> None:
        raise NotImplementedError

    def send_query(self, count_in_m: int) -> None:
        self.my_query_nonce += 1
        q = Query(self.my_query_nonce, self.my_color)
        self.answer_ip[q.id] = Answer(count_in_m)
        self._p.network().send(q, self, self.random_remotes())


def dominant_color(nodes) -> List[int]:
    colors = [0, 0, 0]
    for n in nodes:
        colors[n.my_color] += 1
    return colors


def init_two_colors(protocol, node_factory) -> None:
    """Shared init: build nodes_av nodes, color node 0 red and node 1 blue,
    both start querying (Slush.java:62-74 == Snowflake.java:76-88)."""
    net = protocol.network()
    for _ in range(protocol.params.nodes_av):
        net.add_node(node_factory(protocol))
    uncolored1 = net.get_node_by_id(0)
    uncolored2 = net.get_node_by_id(1)
    uncolored1.my_color = 1
    uncolored1.send_query(1)
    uncolored2.my_color = 2
    uncolored2.send_query(1)


def color_play(protocol, node_continues, graph_path: Optional[str], verbose: bool):
    """The shared `play` driver: per-10ms colored-node series, 10 rounds,
    continue while any node still iterates and neither color holds exactly
    100 nodes — the reference's hardcoded-100 quirk, kept
    (Slush.java:222-268 == Snowflake.java:234-282)."""

    class _Getter(SH.StatsGetter):
        def fields(self):
            return ["avg"]

        def get(self, live_nodes):
            colors = dominant_color(live_nodes)
            if verbose:
                print(
                    f"Colored nodes by the numbers: {colors[0]} remain uncolored "
                    f"{colors[1]} are red {colors[2]} are blue."
                )
            return SH.get_stats_on(live_nodes, lambda n: colors[n.my_color])

    ppt = ProgressPerTime(
        protocol, "", "Number of y-Colored Nodes", _Getter(), 10, None, 10, verbose
    )

    def cont_if(p1) -> bool:
        colors = dominant_color(p1.network().all_nodes)
        for gn in p1.network().all_nodes:
            if (node_continues(gn) and colors[1] != 100) or (
                node_continues(gn) and colors[2] != 100
            ):
                return True
        return False

    return ppt.run(cont_if, graph_path)

"""Batched ETHPoW: Bernoulli mining on the TPU, the blockchain family's
entry to the batched path.

Re-expression of protocols/ethpow/ETHPoW.java + ETHMiner.java (via the
oracle port protocols/ethpow.py) as a 10 ms-stepped `lax.while_loop` over
a preallocated block table — the SURVEY §7 step-7 design:

  * block table `[B]` per replica: parent idx, height, producer,
    proposal time, difficulty, total difficulty (relative to genesis),
    plus a dense arrival matrix `[B, M]` (one row scattered per mined
    block: producer at t, everyone else at t+1+latency — send_all,
    ETHMiner.java:152-163).
  * mining is one Bernoulli trial per miner per 10 ms beat
    (mine10ms, ETHMiner.java:118-129) with success probability
    1 - (1 - 1/difficulty)^(hashPower*2^30/100) (solveIn10ms,
    ETHMiner.java:225-231), computed as 1 - exp(-hp/difficulty): the
    per-hash probability ~5e-16 underflows float32, the exponential form
    is exact to O(n*p^2) ~ 1e-16.
  * fork choice by total difficulty with prefer-own-block on ties
    (ETHPoW.java:299-310, ETHMiner best :337-348) — an argmax over the
    arrived blocks per miner per beat; on exact ties the own block wins,
    otherwise the lowest block index (earliest created) stands in for the
    oracle's keep-first-seen order.
  * Constantinople difficulty (ETHPoW.java:284-296) from the mainnet
    genesis (height 7_951_081, difficulty 1_949_482_043_446_410 —
    ETHPoW.java:158-164), so the EIP-1234 bomb term is the live 2^27
    branch exactly as in the oracle.
  * a new head (own or received) restarts mining on it with a fresh
    candidate stamped at the restart beat (startNewMining,
    ETHMiner.java:133-141) — same next-beat timing as the oracle's
    in_mining=None + next mine10ms.

Byzantine miners (byz_class_name, miner at pos 1 like ETHPoW.java:78-87):
ETHSelfishMiner and ETHSelfishMiner2 (Eyal-Sirer algorithm 1 and the
total-difficulty variant, ETHSelfishMiner.java / ETHSelfishMiner2.java via
the oracle port) run on the batched path — withheld blocks are table rows
whose arrival is INT32_MAX for everyone but the producer, the private
chain is a bool[B] `withheld` mask, and the release walks (competing-block
search + suffix broadcast) are scalar `lax.while_loop`s over the parent
array.  The reference's send_all_mined quirk — the hook drops withheld
blocks instead of broadcasting them (ETHMiner.java:165-171) — is kept
verbatim.  Same-beat simultaneity approximation: of several external
blocks arriving in one 10 ms beat only the best (max total difficulty) is
processed as `on_received_block`; the others can't have beaten it for
other_miners_head anyway.  The RL agent miner (ETHMinerAgent.java) runs
batched too — withhold-always mining, best-head tracking and the
overtaken-block auto-release live in `_agent_receive`, explicit releases
in `agent_apply_action`, and the vectorized decision loop (R lockstep
replicas per policy step) is `ethpow_env.BatchedMinerEnv`; only the CSV
decision logger (ETHAgentMiner.java) stays oracle-side.

Deliberate simplifications (the spike's documented scope — see
docs/batched_blockchain_design.md for the fork-choice design note and the
Casper/Dfinity plan):

  * no uncles: possibleUncles is a bounded DAG walk the batched table
    can do, but the spike keeps y=1 in the difficulty formula and skips
    uncle rewards — block-interval dynamics are uncle-independent at the
    reference's own default (0 uncles until forks are common);
  * difficulty/total difficulty in float32, total difficulty stored
    RELATIVE to genesis so ~1e18 accumulations keep ~2^-24 relative
    precision (the absolute mainnet genesis td 1.06e22 would eat one
    whole block difficulty per float32 ulp);
  * same-beat arrivals are processed simultaneously; 10 ms quantization
    of arrivals (vs the oracle's 1 ms) is negligible against ~13 s block
    intervals.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..core.latency import LatencyStatic, vec_latency
from ..core.node import Node, build_node_columns
from ..core.registries import registry_network_latencies, registry_node_builders
from ..engine.rng import hash32, pseudo_delta, uniform_u01
from ..utils.javarand import JavaRandom
from .ethpow import ETHPoWParameters

INT32_MAX = np.int32(2**31 - 1)
GENESIS_DIFFICULTY = 1_949_482_043_446_410.0
GENESIS_HEIGHT = 7_951_081  # mainnet block (ETHPoW.java:158-164)
TOTAL_HASH_POWER_GHS = 200 * 1024  # ETHPoW.java:72
BEAT_MS = 10
SELFISH_ID = 1  # the bad node is always at pos 1 (ETHPoW.java:78-87)

# byz_class_name -> batched strategy id (pos-1 miner, ETHPoW.java:78-87)
BATCHED_BYZ = {
    "ETHMiner": 0,
    "ETHSelfishMiner": 1,
    "ETHSelfishMiner2": 2,
    # the stepwise RL bridge, vectorized: mining/receive semantics live
    # here (withhold + auto-release of overtaken blocks); the decision
    # loop is ethpow_env.BatchedMinerEnv
    "ETHMinerAgent": 3,
}


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class EthPowState:
    """One replica's simulation state (a pytree)."""

    time: jnp.ndarray  # int32 scalar
    seed: jnp.ndarray  # int32 scalar
    # block table
    n_blocks: jnp.ndarray  # int32 scalar (slot 0 = genesis)
    parent: jnp.ndarray  # int32[B]
    height: jnp.ndarray  # int32[B]
    producer: jnp.ndarray  # int32[B], -1 = genesis
    b_time: jnp.ndarray  # int32[B] proposal time (mining start)
    diff: jnp.ndarray  # float32[B]
    td: jnp.ndarray  # float32[B], relative to genesis
    arrival: jnp.ndarray  # int32[B, M]
    overflowed: jnp.ndarray  # int32 scalar: blocks lost to a full table
    # per-miner state
    head: jnp.ndarray  # int32[M]
    father: jnp.ndarray  # int32[M] (mining candidate's parent)
    cand_time: jnp.ndarray  # int32[M]
    cand_diff: jnp.ndarray  # float32[M]
    mining: jnp.ndarray  # bool[M]
    blocks_mined: jnp.ndarray  # int32[M]
    # selfish-miner columns (inert when no byz strategy is configured)
    pmb: jnp.ndarray  # int32 scalar: private_miner_block idx, -1 = None
    omh: jnp.ndarray  # int32 scalar: other_miners_head idx
    withheld: jnp.ndarray  # bool[B]: mined_to_send set

    def tree_flatten(self):
        return (
            tuple(getattr(self, f.name) for f in dataclasses.fields(self)),
            None,
        )

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)


class BatchedEthPow:
    """The jittable simulation: binds the miner population + latency model
    to a 10 ms-stepped transition over EthPowState."""

    def __init__(
        self,
        params: Optional[ETHPoWParameters] = None,
        b_max: int = 512,
        seed: int = 0,
    ):
        params = params or ETHPoWParameters()
        if params.byz_class_name:
            key = params.byz_class_name.rsplit(".", 1)[-1]
            if key not in BATCHED_BYZ:
                raise NotImplementedError(
                    f"batched ETHPoW supports {sorted(BATCHED_BYZ)} as "
                    "byz_class_name; the CSV decision logger (ETHAgentMiner) "
                    "runs on the oracle (protocols/ethpow.py)"
                )
            self.variant = BATCHED_BYZ[key]
        else:
            self.variant = None
        self.selfish = self.variant in (1, 2)
        self.agent = self.variant == 3
        self.params = params
        self.b_max = b_max
        self.m = params.number_of_miners
        nb = registry_node_builders.get_by_name(params.node_builder_name)
        self.latency = registry_network_latencies.get_by_name(
            params.network_latency_name
        )
        rd = JavaRandom(seed)
        nodes = [Node(rd, nb) for _ in range(self.m)]
        city_index = getattr(self.latency, "city_index", None)
        self.cols = build_node_columns(nodes, city_index)
        self.static = LatencyStatic.from_columns(self.cols)
        # hash-power split (ETHPoW.java:70-87): miner 1 takes the byz
        # share, honest miners split the remainder evenly
        total = TOTAL_HASH_POWER_GHS
        byz_hp = int(total * params.byz_mining_ratio) if self.variant is not None else 0
        honest_n = self.m if byz_hp == 0 else self.m - 1
        honest_hp = (total - byz_hp) // honest_n
        hp = np.full(self.m, honest_hp, np.float64)
        if self.variant is not None:
            hp[SELFISH_ID] = byz_hp
        # P(success per 10 ms) = 1 - exp(-hashes_per_10ms / difficulty)
        self.hp_per_10ms = jnp.asarray(hp * (1024.0**3) / 100.0, jnp.float32)

    # -- state ---------------------------------------------------------------
    def init_state(self, seed: int = 0) -> EthPowState:
        b, m = self.b_max, self.m
        zi = lambda shape: jnp.zeros(shape, jnp.int32)
        arrival = jnp.full((b, m), INT32_MAX, jnp.int32)
        arrival = arrival.at[0].set(0)  # genesis known to everyone at t=0
        return EthPowState(
            time=jnp.int32(1),
            seed=jnp.int32(seed),
            n_blocks=jnp.int32(1),
            parent=zi(b),
            height=jnp.full(b, GENESIS_HEIGHT, jnp.int32),
            producer=jnp.full(b, -1, jnp.int32),
            b_time=zi(b),
            diff=jnp.full(b, GENESIS_DIFFICULTY, jnp.float32),
            td=jnp.zeros(b, jnp.float32),
            arrival=arrival,
            overflowed=jnp.int32(0),
            head=zi(m),
            father=zi(m),
            cand_time=zi(m),
            cand_diff=jnp.full(m, GENESIS_DIFFICULTY, jnp.float32),
            mining=jnp.zeros(m, bool),
            blocks_mined=zi(m),
            pmb=jnp.int32(-1),
            omh=jnp.int32(0),  # genesis (ETHSelfishMiner.java ctor)
            withheld=jnp.zeros(b, bool),
        )

    # -- difficulty (ETHPoW.java:284-296; low-height bomb quirk kept) --------
    def _calc_difficulty(self, f_diff, f_time, f_height, ts):
        gap = ((ts - f_time) // 9000).astype(jnp.float32)
        ugap = jnp.maximum(-99.0, 1.0 - gap)  # y = 1: no uncles in the spike
        diff = (f_diff / 2048.0) * ugap
        periods = (f_height - 4_999_999) // 100_000
        bomb = jnp.where(
            periods > 1,
            jnp.exp2((periods - 2).astype(jnp.float32)),
            diff,  # the reference's own low-height behavior
        )
        return f_diff + diff + bomb

    # -- selfish receive phase (once per beat, scalar per replica) -----------
    def _selfish_receive(self, s: EthPowState, t, new_head):
        """on_received_block for the miner at pos 1, applied to the best
        newly-arrived external block of this beat.

        Variant 1 = ETHSelfishMiner.java:56-115 (height-delta algorithm),
        variant 2 = ETHSelfishMiner2.java:55-81 (total-difficulty walk).
        Returns (omh, withheld, arrival, force_restart)."""
        sm = SELFISH_ID
        m = self.m
        mids = jnp.arange(m, dtype=jnp.int32)
        prod, par, hgt, td = s.producer, s.parent, s.height, s.td
        arr_sm = s.arrival[:, sm]

        newly = (arr_sm > t - BEAT_MS) & (arr_sm <= t) & (prod != sm) & (prod >= 0)
        rcv = jnp.argmax(jnp.where(newly, td, -1.0)).astype(jnp.int32)
        # omh = best(omh, rcv): rcv is never ours, so a tie keeps omh
        # (ETHPoW.best :337-348); "if omh is not rcv: return"
        act = jnp.any(newly) & (td[rcv] > td[s.omh])
        omh = jnp.where(act, rcv, s.omh)

        ph = jnp.where(s.pmb >= 0, hgt[s.pmb], 0)
        safe_pmb = jnp.maximum(s.pmb, 0)

        if self.variant == 1:
            delta_p = ph - (hgt[rcv] - 1)
            lose = act & (delta_p <= 0)  # "they won: we move to their chain"
            rel = act & (delta_p > 0)
            far = rel & (delta_p > 2)
            # far ahead: walk down to the oldest withheld block still above
            # rcv's height (ETHSelfishMiner.java:96-103)
            ts = lax.while_loop(
                lambda i: far & s.withheld[par[i]] & (hgt[i] > hgt[rcv]),
                lambda i: par[i],
                safe_pmb,
            )
            # if we couldn't reach rcv's height, check the ancestor at that
            # height still beats rcv — otherwise sending can't win: return
            need = far & (hgt[ts] != hgt[rcv])
            f = lax.while_loop(
                lambda i: need & (hgt[i] != hgt[rcv]) & (i != 0),
                lambda i: par[i],
                ts,
            )
            cancel = need & (td[f] < td[rcv])
            do_rel = rel & ~cancel
        else:  # variant 2
            lose = act & (new_head[SELFISH_ID] == rcv)  # "if self.head is rcv"
            rel = act & ~lose & (s.pmb >= 0)
            # walk toward the oldest own block whose parent still beats rcv
            # on total difficulty (ETHSelfishMiner2.java:66-71)
            ts = lax.while_loop(
                lambda i: rel & (i != 0) & (hgt[i] >= hgt[rcv]) & (td[par[i]] > td[rcv]),
                lambda i: par[i],
                safe_pmb,
            )
            do_rel = rel

        # losing clears mined_to_send via send_all_mined, whose hook DROPS
        # the blocks for selfish miners (ETHMiner.java:165-171 quirk), then
        # restarts mining on the head
        withheld = jnp.where(lose, jnp.zeros_like(s.withheld), s.withheld)

        # release loop: send to_send and its withheld own ancestors
        # (ETHSelfishMiner.java:105-114); each send_block samples per-dest
        # latency for its own event, arrival at t+1+latency (send_block
        # :315-322 -> send_all)
        sm_vec = jnp.full(m, sm, jnp.int32)

        def rl_cond(c):
            omh_, wh_, ar_, i = c
            return do_rel & (i > 0) & (prod[i] == sm) & wh_[i]

        def rl_body(c):
            omh_, wh_, ar_, i = c
            omh_ = jnp.where(td[i] >= td[omh_], i, omh_)  # best: own wins ties
            ev = hash32(s.seed, t, i, jnp.int32(0x5E1F))
            dlt = pseudo_delta(mids, ev)
            lat = vec_latency(self.latency, self.static, sm_vec, mids, dlt)
            row = jnp.where(mids == sm, ar_[i, sm], t + 1 + lat)
            return (omh_, wh_.at[i].set(False), ar_.at[i].set(row), par[i])

        omh, withheld, arrival, _ = lax.while_loop(
            rl_cond, rl_body, (omh, withheld, s.arrival, ts)
        )
        return omh, withheld, arrival, lose

    # -- agent receive phase (ETHMinerAgent.java:187-204) --------------------
    def _release_rows(self, s: EthPowState, t, rel_mask, tag):
        """Arrival rows for every block in rel_mask: one send event per
        released block, destinations at t+1+latency (action_send_oldest ->
        send_block -> send_all); the producer's own entry is untouched."""
        m, b = self.m, self.b_max
        mids = jnp.arange(m, dtype=jnp.int32)
        bids = jnp.arange(b, dtype=jnp.int32)
        ev = hash32(s.seed, t, bids, tag)  # [B]
        to_idx = jnp.broadcast_to(mids[None, :], (b, m))
        delta = pseudo_delta(to_idx, ev[:, None])
        lat = vec_latency(
            self.latency,
            self.static,
            jnp.full((b * m,), SELFISH_ID, jnp.int32),
            to_idx.reshape(-1),
            delta.reshape(-1),
        ).reshape(b, m)
        # min() keeps the producer's own earlier arrival and is idempotent
        rows = jnp.minimum(s.arrival, t + 1 + lat)
        return jnp.where(rel_mask[:, None], rows, s.arrival)

    def _agent_receive(self, s: EthPowState, t):
        """on_received_block for the RL agent at pos 1: other_miners_head =
        best(omh, rcv); withheld blocks the public chain has overtaken
        (youngest.height <= omh.height) auto-release oldest-first — the
        loop at ETHMinerAgent.java:196-203, i.e. the private chain's
        bottom segment with height <= height[omh] (releases in that loop
        never advance omh).  A scalar release walk like the selfish
        variants': zero iterations on the (typical) beat with nothing
        overtaken."""
        sm = SELFISH_ID
        m = self.m
        mids = jnp.arange(m, dtype=jnp.int32)
        prod, par, hgt, td = s.producer, s.parent, s.height, s.td
        arr_sm = s.arrival[:, sm]
        newly = (arr_sm > t - BEAT_MS) & (arr_sm <= t) & (prod != sm) & (prod >= 0)
        rcv = jnp.argmax(jnp.where(newly, td, -1.0)).astype(jnp.int32)
        act = jnp.any(newly) & (td[rcv] > td[s.omh])
        omh = jnp.where(act, rcv, s.omh)

        # walk from the private tip down to the highest overtaken block,
        # then release it and its withheld ancestors
        start = lax.while_loop(
            lambda i: (i > 0) & (hgt[i] > hgt[omh]),
            lambda i: par[i],
            jnp.maximum(s.pmb, 0),
        )
        sm_vec = jnp.full(m, sm, jnp.int32)

        def rl_cond(c):
            wh_, ar_, i = c
            return (i > 0) & wh_[i]

        def rl_body(c):
            wh_, ar_, i = c
            ev = hash32(s.seed, t, i, jnp.int32(0xA6E7))
            dlt = pseudo_delta(mids, ev)
            lat = vec_latency(self.latency, self.static, sm_vec, mids, dlt)
            row = jnp.where(mids == sm, ar_[i, sm], t + 1 + lat)
            return (wh_.at[i].set(False), ar_.at[i].set(row), par[i])

        withheld, arrival, _ = lax.while_loop(
            rl_cond, rl_body, (s.withheld, s.arrival, start)
        )
        return omh, withheld, arrival

    def agent_apply_action(self, s: EthPowState, k) -> EthPowState:
        """send_mined_blocks(k) (ETHMinerAgent.java:68-88): release the k
        OLDEST withheld private blocks.  omh advances to the highest
        released block that overtakes it (action_send_oldest_block_mined);
        an emptied private chain clears private_miner_block.  Java's
        post-decrement loop leaves howMany at -1 after a fully-honored k,
        so the startNewMining restamp fires ONLY when k exceeded the
        available blocks by exactly one — never on k=0 (the env's default
        keep-withholding action) and never on a fully-honored release
        (ethpow.py send_mined_blocks, kept bit-exact to the reference)."""
        sm = SELFISH_ID
        hgt = s.height
        kk = jnp.maximum(jnp.int32(k), 0)
        wh_h = jnp.where(s.withheld, hgt, INT32_MAX)
        low = jnp.min(wh_h)
        rel = s.withheld & (hgt < low + kk)
        arrival = self._release_rows(s, s.time, rel, jnp.int32(0xAC70))
        withheld = s.withheld & ~rel
        top = jnp.argmax(jnp.where(rel, hgt, -1)).astype(jnp.int32)
        omh = jnp.where(jnp.any(rel) & (hgt[top] > hgt[s.omh]), top, s.omh)

        # Java howMany ends at 0 iff k == |withheld| + 1 -> only then
        # start_new_mining(head) restamps the candidate (see docstring)
        avail = jnp.sum(s.withheld.astype(jnp.int32))
        restart = (
            (kk == avail + 1)
            & s.mining[sm]
            & (s.pmb >= 0)
        )
        head = s.head[sm]
        father = s.father.at[sm].set(jnp.where(restart, head, s.father[sm]))
        cand_time = s.cand_time.at[sm].set(
            jnp.where(restart, s.time, s.cand_time[sm])
        )
        new_diff = self._calc_difficulty(
            s.diff[head], s.b_time[head], s.height[head], s.time
        )
        cand_diff = s.cand_diff.at[sm].set(
            jnp.where(restart, new_diff, s.cand_diff[sm])
        )

        pmb = jnp.where(jnp.any(withheld), s.pmb, -1)
        return dataclasses.replace(
            s,
            arrival=arrival,
            withheld=withheld,
            omh=omh,
            pmb=pmb,
            father=father,
            cand_time=cand_time,
            cand_diff=cand_diff,
        )

    # -- one 10 ms beat ------------------------------------------------------
    def _beat(self, s: EthPowState) -> EthPowState:
        t = s.time
        m, b = self.m, self.b_max
        mids = jnp.arange(m, dtype=jnp.int32)

        # 1. fork choice over arrived blocks (ETHMiner.onBlock + best):
        # max total difficulty; exact ties prefer the own block, else the
        # earliest-created (lowest index)
        arrived = s.arrival <= t  # [B, M]
        td_m = jnp.where(arrived, s.td[:, None], -jnp.inf)
        mx = jnp.max(td_m, axis=0)  # [M]
        is_max = td_m == mx[None, :]
        own = s.producer[:, None] == mids[None, :]
        own_max = is_max & own
        has_own = jnp.any(own_max, axis=0)
        first_any = jnp.argmax(is_max, axis=0).astype(jnp.int32)
        first_own = jnp.argmax(own_max, axis=0).astype(jnp.int32)
        new_head = jnp.where(has_own, first_own, first_any)

        # 1b. selfish receive phase (arrival events land before this beat's
        # mining trial; a forced restart = start_new_mining(head) after
        # losing the race)
        if self.selfish:
            omh, withheld, arrival_in, lose = self._selfish_receive(s, t, new_head)
        elif self.agent:
            omh, withheld, arrival_in = self._agent_receive(s, t)
            lose = None
        else:
            omh, withheld, arrival_in = s.omh, s.withheld, s.arrival
            lose = None

        # 2. head change (or no candidate yet) restarts mining on the head
        # with a fresh candidate stamped now (startNewMining)
        restart = (new_head != s.head) | ~s.mining
        if lose is not None:
            restart = restart | (lose & (mids == SELFISH_ID))
        father = jnp.where(restart, new_head, s.father)
        cand_time = jnp.where(restart, t, s.cand_time)
        cand_diff = jnp.where(
            restart,
            self._calc_difficulty(
                s.diff[new_head], s.b_time[new_head], s.height[new_head], t
            ),
            s.cand_diff,
        )

        # 3. one Bernoulli trial per miner (mine10ms)
        thresh = 1.0 - jnp.exp(-self.hp_per_10ms / cand_diff)
        u = uniform_u01(s.seed, t, mids, jnp.int32(0xE70))
        success = u < thresh

        # 4. append found blocks to the table (capacity-guarded)
        rank = jnp.cumsum(success.astype(jnp.int32)) - 1
        idx = s.n_blocks + rank
        fits = success & (idx < b)
        slot = jnp.where(fits, idx, b)  # OOB -> dropped
        new_diff_v = cand_diff
        new_td = s.td[father] + new_diff_v
        parent = s.parent.at[slot].set(father, mode="drop")
        height = s.height.at[slot].set(s.height[father] + 1, mode="drop")
        producer = s.producer.at[slot].set(mids, mode="drop")
        b_time = s.b_time.at[slot].set(cand_time, mode="drop")
        diff = s.diff.at[slot].set(new_diff_v, mode="drop")
        td = s.td.at[slot].set(new_td, mode="drop")

        # arrivals: producer immediately; everyone else at t+1+latency
        # (sendBlock -> sendAll, ETHMiner.java:152-163)
        static = self.static
        from_idx = jnp.repeat(mids, m)  # [M*M]: each miner to every dest
        to_idx = jnp.tile(mids, m)
        ev_seed = hash32(s.seed, t, from_idx, jnp.int32(0xB10C))
        delta = pseudo_delta(to_idx, ev_seed)
        lat = vec_latency(self.latency, static, from_idx, to_idx, delta)
        arr = (t + 1 + lat).reshape(m, m)
        arr = jnp.where(jnp.eye(m, dtype=bool), t, arr)  # own block now
        if self.selfish or self.agent:
            # the private miner withholds: its block reaches only itself
            # (send_mined_block returns False, ETHSelfishMiner.java:46-48,
            # ETHMinerAgent.java:63-65)
            sm_row = jnp.where(mids == SELFISH_ID, t, INT32_MAX)
            arr = arr.at[SELFISH_ID].set(sm_row)
        arrival = arrival_in.at[slot].set(arr, mode="drop")

        n_ok = jnp.sum(fits.astype(jnp.int32))
        lost = jnp.sum((success & ~fits).astype(jnp.int32))

        # 4b. selfish on_mined_block (ETHSelfishMiner[2].java:38-54, same in
        # both variants): track the private block; at delta_p == 0 with a
        # 2-deep own chain, adopt it as other_miners_head and clear the
        # withheld set (send_all_mined's hook-drop quirk)
        pmb = s.pmb
        if self.agent:
            # the auto-release loop (ETHMinerAgent.java:196-203) goes
            # through sendMinedBlocks(1), whose final guard nulls
            # privateMinerBlock once minedToSend empties; without this a
            # stale pmb would pass agent_apply_action's pmb>=0 gate where
            # the oracle sees private_miner_block=None (ADVICE r4)
            pmb = jnp.where(jnp.any(withheld), pmb, jnp.int32(-1))
        if self.selfish or self.agent:
            sm = SELFISH_ID
            k = idx[sm]
            mined_ok = success[sm] & fits[sm]
            withheld = withheld.at[jnp.where(mined_ok, k, b)].set(True, mode="drop")
            pmb = jnp.where(mined_ok, k, pmb)
        if self.selfish:
            f_sm = father[sm]
            hk = s.height[f_sm] + 1
            td_k = new_td[sm]
            delta_pm = hk - (s.height[omh] - 1)
            depth2 = (s.producer[f_sm] == sm) & (s.producer[s.parent[f_sm]] != sm)
            publish0 = mined_ok & (delta_pm == 0) & depth2
            omh = jnp.where(publish0 & (td_k >= s.td[omh]), k, omh)
            withheld = jnp.where(publish0, jnp.zeros_like(withheld), withheld)

        return EthPowState(
            time=t + BEAT_MS,
            seed=s.seed,
            n_blocks=s.n_blocks + n_ok,
            parent=parent,
            height=height,
            producer=producer,
            b_time=b_time,
            diff=diff,
            td=td,
            arrival=arrival,
            overflowed=s.overflowed + lost,
            head=new_head,
            father=father,
            cand_time=cand_time,
            cand_diff=cand_diff,
            # a successful miner stops (in_mining = None) and restarts on
            # its own block next beat, exactly like the oracle
            mining=~success,
            blocks_mined=s.blocks_mined + success.astype(jnp.int32),
            pmb=pmb,
            omh=omh,
            withheld=withheld,
        )

    # -- run -----------------------------------------------------------------
    @functools.partial(jax.jit, static_argnums=(0, 2))
    def run_ms(self, state: EthPowState, ms: int) -> EthPowState:
        end = state.time + ms

        def cond(s):
            return s.time < end

        return lax.while_loop(cond, self._beat, state)

    @functools.partial(jax.jit, static_argnums=(0, 2))
    def run_ms_batched(self, states: EthPowState, ms: int) -> EthPowState:
        return jax.vmap(lambda s: self.run_ms(s, ms))(states)


def replicate_ethpow(state: EthPowState, n_replicas: int, seeds=None) -> EthPowState:
    if seeds is None:
        seeds = np.arange(n_replicas, dtype=np.int32)
    seeds = jnp.asarray(seeds, jnp.int32)
    tiled = jax.tree_util.tree_map(
        lambda a: jnp.broadcast_to(a, (n_replicas,) + a.shape), state
    )
    return dataclasses.replace(tiled, seed=seeds)


def chain_producers(state: EthPowState, replica: Optional[int] = None) -> np.ndarray:
    """Host-side: producer ids along the PUBLIC winning chain, tip to
    genesis (exclusive).  The tip is the best block the observer (miner 0,
    honest) has actually received — the oracle comparator walks
    observer.head, so counting the selfish miner's still-withheld private
    blocks would systematically overstate its revenue.  The batched analog
    of try_miner's revenue ratio without uncle rewards
    (ETHMiner.java:234-308)."""
    if replica is not None:
        state = jax.tree_util.tree_map(lambda a: a[replica], state)
    td = np.asarray(state.td)
    n = int(state.n_blocks)
    parent = np.asarray(state.parent)
    producer = np.asarray(state.producer)
    known = np.asarray(state.arrival)[:n, 0] <= int(state.time)
    cur = int(np.argmax(np.where(known, td[:n], -1.0)))
    out = []
    while cur != 0:
        out.append(int(producer[cur]))
        cur = int(parent[cur])
    return np.asarray(out, np.int32)


def selfish_revenue_ratio(state: EthPowState, replica: Optional[int] = None) -> float:
    """Share of winning-chain blocks produced by the miner at pos 1."""
    pr = chain_producers(state, replica)
    return float((pr == SELFISH_ID).mean()) if len(pr) else 0.0


def chain_intervals(state: EthPowState, replica: Optional[int] = None) -> np.ndarray:
    """Host-side: proposal-time gaps along the winning chain (the batched
    analog of walking observer.head.parent — BlockChainNode.java:28-44)."""
    if replica is not None:
        state = jax.tree_util.tree_map(lambda a: a[replica], state)
    td = np.asarray(state.td)
    n = int(state.n_blocks)
    parent = np.asarray(state.parent)
    b_time = np.asarray(state.b_time)
    cur = int(np.argmax(td[:n]))
    times = []
    while cur != 0:
        times.append(int(b_time[cur]))
        cur = int(parent[cur])
    times.append(0)
    times.reverse()
    return np.diff(np.asarray(times))

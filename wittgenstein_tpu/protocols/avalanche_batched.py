"""Batched Avalanche family (Slush / Snowflake): repeated random sampling
with an alpha threshold, as vectorized per-tick kernels.

Reference semantics: protocols/Slush.java and protocols/Snowflake.java —
the shared Query/AnswerQuery machinery (Slush.java:86-220 ==
Snowflake.java:95-232) plus the per-protocol onAnswer accounting
(Slush.java:161-176 round/M; Snowflake.java:170-188 cnt/B).

Design notes (TPU-first, not a port):

  * a node has AT MOST ONE query in flight (send_query fires only at color
    adoption or when the previous query's K answers are all in), so the
    per-node answer book `answer_ip` collapses to two counter columns
    `cf[N, 3]` plus an `active[N]` mask — no map, no query ids;
  * `random_remotes`' rejection loop (K distinct uniform picks,
    Slush.java:126-137) becomes `top_k` over per-(node, nonce) hashed
    random keys with the self-key pinned to INT32_MIN: an exact
    sample-without-replacement, drawn in one shot for every querying node;
  * same-tick query adoption races resolve by lowest ring slot (the oracle
    processes them in LIFO ms order; documented ordering delta of the
    batched engine) — all same-tick queries are answered with the
    post-adoption color.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from ..core.node import build_node_columns
from ..core.registries import registry_network_latencies
from ..engine import BatchedNetwork, BatchedProtocol, Emission
from ..engine.rng import hash32
from .slush import Slush, SlushParameters
from .snowflake import Snowflake, SnowflakeParameters

INT32_MIN = jnp.int32(-(2**31))


class BatchedAvalanche(BatchedProtocol):
    """Shared engine for both protocols; `mode` picks the onAnswer rule."""

    MSG_TYPES = ["QUERY", "ANSWER"]
    PAYLOAD_WIDTH = 1  # the sender's color
    TICK_INTERVAL = None  # pure message protocol: engine may skip empty ms

    def __init__(self, params, mode: str):
        assert mode in ("slush", "snowflake")
        self.params = params
        self.mode = mode
        self.n_nodes = params.nodes_av
        self.k = params.k
        self.ak = params.ak

    def proto_init(self, n_nodes: int):
        color = jnp.zeros(n_nodes, jnp.int32)
        # init_two_colors (Slush.java:62-74): node 0 red, node 1 blue, both
        # with a query in flight from t=0
        color = color.at[0].set(1).at[1].set(2)
        active = jnp.zeros(n_nodes, bool).at[0].set(True).at[1].set(True)
        return {
            "color": color,
            "iter": jnp.zeros(n_nodes, jnp.int32),  # Slush round / Snowflake cnt
            "active": active,
            "cf": jnp.zeros((n_nodes, 3), jnp.int32),  # answers by color
            "nonce": jnp.zeros(n_nodes, jnp.int32),  # per-node query counter
        }

    # -- K distinct random remotes (Slush.java:126-137) ----------------------
    def _query_emission(self, state, start, color, nonce):
        """Emission: every node in `start` queries K distinct uniform
        remotes (excluding itself) with its current color."""
        n, k = self.n_nodes, self.k
        rows = jnp.arange(n, dtype=jnp.int32)
        keys = hash32(
            state.seed, jnp.int32(7701), rows[:, None], nonce[:, None],
            jnp.arange(n, dtype=jnp.int32)[None, :],
        )
        keys = keys.at[rows, rows].set(INT32_MIN)  # never sample self
        _, picks = jax.lax.top_k(keys, k)  # [N, K] distinct ids
        return Emission(
            mask=jnp.repeat(start, k),
            from_idx=jnp.repeat(rows, k),
            to_idx=picks.reshape(-1).astype(jnp.int32),
            mtype=self.mtype("QUERY"),
            payload=jnp.repeat(color, k)[:, None],
        )

    def initial_emissions(self, net, state):
        p = state.proto
        return [self._query_emission(state, p["active"], p["color"], p["nonce"])]

    def deliver(self, net, state, deliver_mask):
        p = self.params
        proto = state.proto
        c = deliver_mask.shape[0]
        to, frm = state.msg_to, state.msg_from
        pay_color = state.msg_payload[:, 0]
        is_q = deliver_mask & (state.msg_type == self.mtype("QUERY"))
        is_a = deliver_mask & (state.msg_type == self.mtype("ANSWER"))

        # -- on_query: uncolored nodes adopt the winning (lowest-slot)
        # query's color and start their own query (Slush.java:141-148)
        color = proto["color"]
        slot = jnp.arange(c, dtype=jnp.int32)
        win = jnp.full(self.n_nodes, c, jnp.int32)
        win = win.at[to].min(
            jnp.where(is_q & (color[to] == 0), slot, c), mode="drop"
        )
        adopts = win < c
        win_color = pay_color[jnp.clip(win, 0, c - 1)]
        color = jnp.where(adopts & (color == 0), win_color, color)

        # every query is answered with the (post-adoption) current color
        em_answer = Emission(
            mask=is_q,
            from_idx=to,
            to_idx=frm,
            mtype=self.mtype("ANSWER"),
            payload=color[to][:, None],
        )

        # -- on_answer accounting: count answers for the active query
        cf = proto["cf"]
        cf = cf.at[to, jnp.clip(pay_color, 0, 2)].add(
            is_a.astype(jnp.int32), mode="drop"
        )
        it = proto["iter"]
        active = proto["active"]
        complete = active & ((cf[:, 1] + cf[:, 2]) >= p.k)
        other = jnp.where(color == 1, 2, 1).astype(jnp.int32)
        rows = jnp.arange(self.n_nodes, dtype=jnp.int32)
        cf_other = cf[rows, other]
        cf_mine = cf[rows, jnp.clip(color, 0, 2)]
        flip = complete & (cf_other > p.ak)
        if self.mode == "slush":
            # Slush.java:161-176: flip on opposing majority; requery while
            # round < M
            cont = complete & (it < p.m)
            it = jnp.where(cont, it + 1, it)
        else:
            # Snowflake.java:170-188: flip resets cnt, confirming majority
            # increments it; requery while cnt <= B
            confirm = complete & ~flip & (cf_mine > p.ak)
            it = jnp.where(flip, 0, jnp.where(confirm, it + 1, it))
            cont = complete & (it <= p.b)
        color = jnp.where(flip, other, color)

        start = cont | adopts
        nonce = proto["nonce"] + start.astype(jnp.int32)
        em_query = self._query_emission(state, start, color, nonce)
        active = (active & ~complete) | start
        cf = jnp.where(complete[:, None], 0, cf)

        state = state._replace(
            proto={
                "color": color,
                "iter": it,
                "active": active,
                "cf": cf,
                "nonce": nonce,
            }
        )
        return state, [em_answer, em_query]

    def all_done(self, state):
        p = state.proto
        return jnp.all(p["color"] > 0) & ~jnp.any(p["active"])


def _make(oracle_cls, params, mode: str, capacity: int, seed: int):
    """Host-side construction: build the oracle's node layout (same builder
    RNG stream → same position/latency distribution), bake into the engine."""
    oracle = oracle_cls(params)
    oracle.init()
    net_o = oracle.network()
    latency = registry_network_latencies.get_by_name(params.network_latency_name)
    city_index = getattr(latency, "city_index", None)
    cols = build_node_columns(net_o.all_nodes, city_index)
    proto = BatchedAvalanche(params, mode)
    net = BatchedNetwork(proto, latency, params.nodes_av, capacity=capacity)
    state = net.init_state(
        cols, seed=seed, proto=proto.proto_init(params.nodes_av)
    )
    return net, state


def make_slush(
    params: Optional[SlushParameters] = None, capacity: int = 1 << 12, seed: int = 0
):
    return _make(Slush, params or SlushParameters(), "slush", capacity, seed)


def make_snowflake(
    params: Optional[SnowflakeParameters] = None,
    capacity: int = 1 << 12,
    seed: int = 0,
):
    return _make(Snowflake, params or SnowflakeParameters(), "snowflake", capacity, seed)

"""Batched GSFSignature: north-star config #2 on the TPU engine.

Re-expression of protocols/GSFSignature.java (via the oracle port
protocols/gsf.py) on the shared bitset-aggregation machinery
(_agg_batched.BitsetAggBase): XOR-relative packed bitsets, per-level
exact-width channel slots + freshest-offer backstop, and a one-slot
verification register committing at t + pairingTime.

GSF specifics vs Handel:

  * a node's level-l sends carry its whole *completed prefix* — the union
    of consecutively complete levels is always the interval [0, 2^k) in
    the XOR layout (getLastFinishedLevel, GSFSignature.java:376-392), so
    the multi-level payload is transmitted as the level-confined content
    (w_l words) plus ONE integer k per message (`in_aux`/`cand_pk`); the
    receiver reconstructs the interval exactly, which is what drives the
    absorb-lower-levels path of updateVerifiedSignatures (:397-411).
  * level sends are budgeted: remainingCalls starts at the level size and
    is reset on improvement (:345-356, :438-443); dissemination stops
    when the budget is exhausted rather than cycling forever.
  * verification candidates are scored with evaluateSig (:478-520):
    completion bonus 1_000_000 - 10*level, otherwise 100_000 - 100*level
    + addedSigs, individual-sig fallback score 1 — and the *global* best
    across levels is verified (no per-level uniform choice, :524-558).
  * every first message from a sender enqueues that sender's individual
    single-bit signature as a separate verification candidate
    (onNewSig, :560-577), tracked here as pending/seen bitsets with the
    lowest-index pending bit as the level's representative candidate.
  * accelerated calls: on improvement, burst the completed prefix to
    acceleratedCallsCount fresh peers of each level the prefix now covers
    (:438-451).
  * no Byzantine attack modes, no desynchronized start, no blacklist
    (nodes can only be down); done nodes keep verifying their queues.

Distribution-parity approximations (as in batched Handel): counter-hash
emission order instead of the shuffled peer lists, channel displacement
instead of an unbounded queue (top-K score-curated candidates), send-time
receiver counters, simultaneous same-ms deliveries.
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
import numpy as np
from jax import lax

from ..core.node import Node, build_node_columns
from ..core.registries import registry_network_latencies, registry_node_builders
from ..engine import BatchedNetwork
from ..engine.rng import hash32
from ..ops.bitops import block_mask, popcount_words, xor_shuffle
from ..utils.javarand import JavaRandom
from ._agg_batched import INT32_MAX, BitsetAggBase
from .gsf import GSFSignatureParameters


class BatchedGSF(BitsetAggBase):
    CAND_SLOTS = 8  # K: score-curated verification candidates per level

    def __init__(self, params: GSFSignatureParameters):
        self.params = params
        self._init_geometry(params.node_count)
        # prefix interval masks: pref_masks[k] = bits [0, 2^k)
        self.pref_masks = np.stack(
            [block_mask(0, 1 << k, self.n_words) for k in range(self.n_levels)]
        )

    def msg_size(self, mtype: int) -> int:
        # Size = level byte + bit field + the aggregated sig + our own sig
        # (SendSigs, GSFSignature.java:143-164)
        expected = 1 if mtype == 0 else 1 << (mtype - 1)
        return 1 + expected // 8 + 96

    # -- state ---------------------------------------------------------------
    def proto_init(self, n_nodes: int, pairing: np.ndarray):
        n, L, K = self.n_nodes, self.n_levels, self.CAND_SLOTS
        own = np.zeros((n, self.n_words), dtype=np.uint32)
        own[:, 0] = 1  # bit 0 = own signature (level 0)
        in_key, in_sig = self._channel_init(n)
        ss = self.CHANNEL_DEPTH + 1
        remaining = np.zeros((n, L), dtype=np.int32)
        for l in range(1, L):
            remaining[:, l] = 1 << (l - 1)
        return {
            "ver": jnp.asarray(own),  # verified union, per level blocks
            "indiv": jnp.zeros((n, self.n_words), jnp.uint32),
            "ind_seen": jnp.zeros((n, self.n_words), jnp.uint32),
            "pend_ind": jnp.zeros((n, self.n_words), jnp.uint32),
            "in_key": in_key,
            "in_sig": in_sig,
            "in_aux": jnp.zeros((n, (L - 1) * ss), jnp.int32),  # prefix k
            "cand_key": jnp.full((n, (L - 1) * K), INT32_MAX, jnp.int32),  # rel
            "cand_pk": jnp.zeros((n, (L - 1) * K), jnp.int32),
            "cand_sig": jnp.zeros((n, K * self.w_total), jnp.uint32),
            "ver_active": jnp.zeros(n, bool),
            "ver_done_t": jnp.zeros(n, jnp.int32),
            "ver_level": jnp.zeros(n, jnp.int32),
            "ver_rel": jnp.zeros(n, jnp.int32),
            "ver_pk": jnp.zeros(n, jnp.int32),
            "ver_single": jnp.zeros(n, bool),  # individual-sig verification
            "ver_sig": jnp.zeros((n, self.w_max), jnp.uint32),
            "remaining": jnp.asarray(remaining),
            "pos": jnp.zeros((n, L), jnp.int32),
            "sig_checked": jnp.zeros(n, jnp.int32),
            "pairing": jnp.asarray(pairing, jnp.int32),
        }

    # -- helpers -------------------------------------------------------------
    def _prefix_k(self, ver):
        """Number of consecutively complete levels from level 1 up
        (getLastFinishedLevel): the verified union is then >= [0, 2^k)."""
        if self.n_levels == 1:
            return jnp.zeros(ver.shape[0], jnp.int32)
        comp = jnp.stack(
            [
                popcount_words(self._blk(ver, l)) == (1 << (l - 1))
                for l in range(1, self.n_levels)
            ],
            axis=1,
        )
        return jnp.sum(jnp.cumprod(comp.astype(jnp.int32), axis=1), axis=1)

    def _eval_sig(self, l: int, sig, ver_b, indiv_b):
        """evaluateSig (GSFSignature.java:478-520) on block-local [N, K, w]
        candidates; sig may be [N, w] too (broadcast over K)."""
        bs = 1 << (l - 1)
        if sig.ndim == ver_b.ndim:
            sig = sig[:, None, :]
        vb = ver_b[:, None, :]
        ib = indiv_b[:, None, :]
        ver_card = popcount_words(ver_b)[:, None]
        sig_card = popcount_words(sig)
        inter = popcount_words(sig & vb) > 0
        with_ind = sig | ib
        with_ind_v = with_ind | vb
        new_total = jnp.where(
            ver_card == 0,
            sig_card,
            jnp.where(inter, popcount_words(with_ind), popcount_words(with_ind_v)),
        )
        added = jnp.where(ver_card == 0, sig_card, new_total - ver_card)
        indiv_fallback = (
            (sig_card == 1) & (popcount_words(sig & ib) == 0)
        ).astype(jnp.int32)
        score = jnp.where(
            added <= 0,
            indiv_fallback,
            jnp.where(
                new_total == bs,
                1_000_000 - l * 10,
                100_000 - l * 100 + added,
            ),
        )
        return jnp.where(ver_card >= bs, 0, score)

    # -- tick phase 1: commit due verifications ------------------------------
    def _commit(self, net, state):
        """updateVerifiedSignatures (GSFSignature.java:379-460)."""
        p = self.params
        proto = state.proto
        t = state.time
        n, L = self.n_nodes, self.n_levels
        ids = jnp.arange(n, dtype=jnp.int32)

        due = proto["ver_active"] & (t >= proto["ver_done_t"])
        ver, indiv = proto["ver"], proto["indiv"]
        remaining = proto["remaining"]
        rel = proto["ver_rel"]
        pk = proto["ver_pk"]

        improved_any = jnp.zeros(n, bool)
        for l in range(1, L):
            bs = 1 << (l - 1)
            m = due & (proto["ver_level"] == l)
            r0 = rel & (bs - 1)
            sig_b = proto["ver_sig"][:, : self.w[l]]
            ver_b = self._blk(ver, l)
            indiv_b = self._blk(indiv, l)

            # individual sig: set the indiv bit first (:383-385)
            single = m & proto["ver_single"]
            oh = self._onehot(r0, self.w[l])
            new_indiv_b = jnp.where(single[:, None], indiv_b | oh, indiv_b)
            # holder.sigs |= indivVerifiedSig (:386)
            sigs = sig_b | new_indiv_b

            # absorb the completed prefix (:397-411): pk >= l means the
            # sender's consecutive-complete levels cover [0, 2^pk), which
            # includes this block and the receiver's levels 1..pk
            absorb = m & (pk >= l)
            interval = jnp.asarray(self.pref_masks)[jnp.minimum(pk, L - 1)]
            newly = popcount_words(interval & ~ver) > 0
            reset_r = absorb & newly
            ver = jnp.where(absorb[:, None], ver | interval, ver)
            ver_b = self._blk(ver, l)  # may now be complete
            full_block = jnp.full((n, 1), (1 << bs) - 1, jnp.uint32) if bs < 32 else jnp.full(
                (n, self.w[l]), 0xFFFFFFFF, jnp.uint32
            )
            sigs = jnp.where(absorb[:, None], full_block, sigs)

            # disjoint sets aggregate (:413-417)
            disjoint = (popcount_words(ver_b) > 0) & (
                popcount_words(sigs & ver_b) == 0
            )
            sigs = jnp.where((m & disjoint)[:, None], sigs | ver_b, sigs)

            # replacement on improvement (:419-431)
            improve = m & (
                (popcount_words(sigs) > popcount_words(ver_b)) | reset_r
            )
            ver = self._blk_write(ver, l, sigs, improve)
            indiv = self._blk_write(indiv, l, new_indiv_b, m)

            # reset send budgets for levels >= l (:421-423)
            lv_idx = jnp.arange(L, dtype=jnp.int32)[None, :]
            sizes = jnp.asarray(
                [0] + [1 << (j - 1) for j in range(1, L)], jnp.int32
            )[None, :]
            remaining = jnp.where(
                improve[:, None] & (lv_idx >= l), sizes, remaining
            )
            improved_any = improved_any | improve

        # accelerated calls (:438-451): after the merges, burst the
        # completed prefix to fresh peers of each level it now covers.
        # Each node committed at exactly one level (ver_level), so one
        # send per target level mm covers every row: burst at mm iff the
        # commit improved, mm > committed level, and the new prefix k
        # reaches mm-1.
        state = state._replace(
            proto=dict(proto, ver=ver, indiv=indiv, remaining=remaining)
        )
        if p.accelerated_calls_count > 0 and L > 2:
            k_new = self._prefix_k(ver)
            lvl = proto["ver_level"]
            acc = p.accelerated_calls_count
            havings = ver | jnp.asarray(self.pref_masks)[jnp.minimum(k_new, L - 1)]
            for mm in range(2, L):
                bsm = 1 << (mm - 1)
                fan = min(acc, bsm)
                proto_c = state.proto
                remaining = proto_c["remaining"]
                burst = improved_any & (lvl < mm) & (k_new >= mm - 1)
                take = jnp.where(
                    burst, jnp.minimum(jnp.maximum(remaining[:, mm], 0), fan), 0
                )
                offset = hash32(state.seed, ids, jnp.int32(mm), t) & (bsm - 1)
                js = jnp.arange(fan, dtype=jnp.int32)
                relb = bsm + (
                    (proto_c["pos"][:, mm, None] + offset[:, None] + js[None, :])
                    & (bsm - 1)
                )
                mask_b = js[None, :] < take[:, None]
                state = state._replace(
                    proto=dict(
                        proto_c, remaining=remaining.at[:, mm].add(-take)
                    )
                )
                content = self._low(havings, mm)
                state = self._send_level(
                    net,
                    state,
                    mm,
                    mask_b.reshape(-1),
                    jnp.repeat(ids, fan),
                    (ids[:, None] ^ relb).reshape(-1),
                    jnp.repeat(content, fan, axis=0),
                    aux=jnp.repeat(k_new, fan),
                )
        proto = state.proto
        ver, indiv, remaining = proto["ver"], proto["indiv"], proto["remaining"]

        total = popcount_words(ver)
        done_now = (
            improved_any & (state.done_at == 0) & ~state.down & (total >= p.threshold)
        )
        state = state._replace(
            done_at=jnp.where(done_now, t, state.done_at),
            proto=dict(
                proto,
                ver=ver,
                indiv=indiv,
                remaining=remaining,
                ver_active=proto["ver_active"] & ~due,
            ),
        )
        return state

    # -- tick phase 2: deliver channel slots into candidates -----------------
    def _channel_deliver(self, net, state):
        """onNewSig (GSFSignature.java:560-577): enqueue the aggregate and,
        once per sender, its individual signature."""
        proto = state.proto
        n, L, K = self.n_nodes, self.n_levels, self.CAND_SLOTS
        rel_mask = (1 << self.rel_bits) - 1

        in_key, due_all, empty_tpl = self._advance_channel(proto["in_key"])

        new_cand_key = proto["cand_key"]
        new_cand_pk = proto["cand_pk"]
        new_cand_sig = proto["cand_sig"]
        new_pend = proto["pend_ind"]
        new_seen = proto["ind_seen"]
        ver, indiv = proto["ver"], proto["indiv"]

        for l in range(1, L):
            bs = 1 << (l - 1)
            ss = self.CHANNEL_DEPTH + 1
            keys = self._key_seg(in_key, l)
            due = self._key_seg(due_all, l)
            rel = keys & rel_mask
            r0 = rel & (bs - 1)
            pk_new = self._key_seg(proto["in_aux"], l)

            sig_new = xor_shuffle(self._sig_seg(proto["in_sig"], l, ss), r0)

            # individual sig enqueue: once per sender per level (the bit
            # position in rel space IS the level block)
            oh_rows = jnp.zeros((n, self.n_words), jnp.uint32)
            for d in range(ss):
                reld = rel[:, d]
                hot = self._onehot(reld, self.n_words)
                oh_rows = oh_rows | jnp.where(due[:, d, None], hot, 0)
            fresh_ind = oh_rows & ~new_seen
            new_seen = new_seen | fresh_ind
            new_pend = new_pend | fresh_ind

            # merge [K existing + ss new] candidates, keep top-K by score
            c_key = proto["cand_key"][:, (l - 1) * K : l * K]
            c_pk = proto["cand_pk"][:, (l - 1) * K : l * K]
            c_sig = self._sig_seg(proto["cand_sig"], l, K)

            all_key = jnp.concatenate(
                [c_key, jnp.where(due, rel, INT32_MAX)], axis=1
            )
            all_pk = jnp.concatenate([c_pk, pk_new], axis=1)
            all_sig = jnp.concatenate([c_sig, sig_new], axis=1)
            valid = all_key != INT32_MAX

            ver_b = self._blk(ver, l)
            indiv_b = self._blk(indiv, l)
            # prefix-carrying candidates are full-block in this level, so
            # the exact evaluateSig on block content scores them correctly
            score = self._eval_sig(l, all_sig, ver_b, indiv_b)
            score = jnp.where(valid, score, -1)
            # drop worthless entries (checkSigs' iterator remove, :532-537)
            score = jnp.where(score == 0, -1, score)

            order = jnp.argsort(-score, axis=1)[:, :K]
            top_ok = jnp.take_along_axis(score, order, axis=1) > 0
            sel_key = jnp.where(
                top_ok, jnp.take_along_axis(all_key, order, axis=1), INT32_MAX
            )
            sel_pk = jnp.take_along_axis(all_pk, order, axis=1)
            sel_sig = jnp.take_along_axis(all_sig, order[..., None], axis=1)

            new_cand_key = new_cand_key.at[:, (l - 1) * K : l * K].set(sel_key)
            new_cand_pk = new_cand_pk.at[:, (l - 1) * K : l * K].set(sel_pk)
            o, wk = self.off[l] * K, self.w[l] * K
            new_cand_sig = new_cand_sig.at[:, o : o + wk].set(
                sel_sig.reshape(n, wk)
            )

        state = state._replace(
            proto=dict(
                proto,
                in_key=jnp.where(due_all, empty_tpl[None, :], in_key),
                cand_key=new_cand_key,
                cand_pk=new_cand_pk,
                cand_sig=new_cand_sig,
                pend_ind=new_pend,
                ind_seen=new_seen,
            )
        )
        return state

    # -- tick phase 3: periodic dissemination --------------------------------
    def _dissemination(self, net, state):
        """doCycle over started levels with send budgets
        (GSFSignature.java:289-343)."""
        p = self.params
        proto = state.proto
        t = state.time
        ids = jnp.arange(self.n_nodes, dtype=jnp.int32)

        on_beat = (t >= 1) & (
            lax.rem(t - 1, jnp.int32(p.period_duration_ms)) == 0
        )
        may_send = on_beat & ~state.down

        k = self._prefix_k(proto["ver"])
        havings = proto["ver"] | jnp.asarray(self.pref_masks)[
            jnp.minimum(k, self.n_levels - 1)
        ]
        new_pos = proto["pos"]
        new_remaining = proto["remaining"]
        for l in range(1, self.n_levels):
            bs = 1 << (l - 1)
            content = self._low(havings, l)
            started = (t >= l * p.timeout_per_level_ms) | (
                popcount_words(content) >= bs
            )
            mask = may_send & started & (new_remaining[:, l] > 0)
            offset = hash32(state.seed, ids, jnp.int32(l)) & (bs - 1)
            rel = (bs + ((new_pos[:, l] + offset) & (bs - 1))).astype(jnp.int32)
            new_pos = new_pos.at[:, l].set(
                jnp.where(mask, new_pos[:, l] + 1, new_pos[:, l])
            )
            new_remaining = new_remaining.at[:, l].add(-mask.astype(jnp.int32))
            state = state._replace(
                proto=dict(state.proto, pos=new_pos, remaining=new_remaining)
            )
            state = self._send_level(
                net, state, l, mask, ids, ids ^ rel, content, aux=k
            )
            new_pos = state.proto["pos"]
            new_remaining = state.proto["remaining"]
        return state

    # -- tick phase 4: start verifications (checkSigs) -----------------------
    def _select(self, net, state):
        """Global best-scored candidate across levels
        (GSFSignature.java:524-558)."""
        proto = state.proto
        t = state.time
        n, L, K = self.n_nodes, self.n_levels, self.CAND_SLOTS
        ids = jnp.arange(n, dtype=jnp.int32)

        free = ~proto["ver_active"] & ~state.down & (t >= 1)
        ver, indiv, pend = proto["ver"], proto["indiv"], proto["pend_ind"]

        best_score = jnp.zeros(n, jnp.int32)
        best_level = jnp.zeros(n, jnp.int32)
        best_rel = jnp.zeros(n, jnp.int32)
        best_pk = jnp.zeros(n, jnp.int32)
        best_kidx = jnp.full(n, -1, jnp.int32)  # -1 = individual pending bit
        new_cand_key = proto["cand_key"]
        for l in range(1, L):
            bs = 1 << (l - 1)
            c_key = proto["cand_key"][:, (l - 1) * K : l * K]
            c_pk = proto["cand_pk"][:, (l - 1) * K : l * K]
            c_sig = self._sig_seg(proto["cand_sig"], l, K)
            valid = c_key != INT32_MAX
            ver_b = self._blk(ver, l)
            indiv_b = self._blk(indiv, l)
            score = jnp.where(valid, self._eval_sig(l, c_sig, ver_b, indiv_b), -1)
            # curation: drop worthless entries permanently
            new_cand_key = new_cand_key.at[:, (l - 1) * K : l * K].set(
                jnp.where(score == 0, INT32_MAX, c_key)
            )
            kbest = jnp.argmax(score, axis=1)
            sbest = jnp.take_along_axis(score, kbest[:, None], axis=1)[:, 0]

            # individual pending representative: lowest pending bit
            pend_b = self._blk(pend, l)
            has_pend = popcount_words(pend_b) > 0
            m_ind = self._lowest_bit(pend_b)
            oh = self._onehot(m_ind & (bs - 1), self.w[l])
            s_ind = self._eval_sig(l, oh[:, None, :], ver_b, indiv_b)[:, 0]
            s_ind = jnp.where(has_pend, s_ind, -1)
            # worthless individuals are dropped too
            pend = self._blk_write(
                pend, l, jnp.where((s_ind == 0)[:, None], pend_b & ~oh, pend_b),
                has_pend & (s_ind == 0),
            )

            use_ind = s_ind > sbest
            l_score = jnp.maximum(sbest, s_ind)
            l_rel = jnp.where(
                use_ind,
                bs + (m_ind & (bs - 1)),
                jnp.take_along_axis(c_key, kbest[:, None], axis=1)[:, 0],
            )
            l_pk = jnp.where(
                use_ind, 0, jnp.take_along_axis(c_pk, kbest[:, None], axis=1)[:, 0]
            )
            l_kidx = jnp.where(use_ind, -1, kbest)

            better = l_score > best_score
            best_score = jnp.where(better, l_score, best_score)
            best_level = jnp.where(better, l, best_level)
            best_rel = jnp.where(better, l_rel, best_rel)
            best_pk = jnp.where(better, l_pk, best_pk)
            best_kidx = jnp.where(better, l_kidx, best_kidx)

        can = free & (best_score > 0)
        sel_single = best_kidx < 0

        # load the chosen sig into the verification register
        ver_sig = proto["ver_sig"]
        for l in range(1, L):
            bs = 1 << (l - 1)
            m = can & (best_level == l)
            c_sig = self._sig_seg(proto["cand_sig"], l, K)
            safe_k = jnp.maximum(best_kidx, 0)
            from_buf = jnp.take_along_axis(c_sig, safe_k[:, None, None], axis=1)[:, 0]
            single = self._onehot(best_rel & (bs - 1), self.w[l])
            sig_l = jnp.where(sel_single[:, None], single, from_buf)
            pad = jnp.zeros((n, self.w_max - self.w[l]), jnp.uint32)
            ver_sig = jnp.where(
                m[:, None], jnp.concatenate([sig_l, pad], axis=1), ver_sig
            )
            # clear the individual pending bit on selection
            pend_b = self._blk(pend, l)
            oh = self._onehot(best_rel & (bs - 1), self.w[l])
            pend = self._blk_write(pend, l, pend_b & ~oh, m & sel_single)

        # remove the chosen buffer candidate
        flat_idx = (best_level - 1) * K + jnp.maximum(best_kidx, 0)
        remove = can & ~sel_single
        safe_row = jnp.where(remove, ids, n)
        new_cand_key = new_cand_key.at[safe_row, flat_idx].set(
            INT32_MAX, mode="drop"
        )

        state = state._replace(
            proto=dict(
                proto,
                cand_key=new_cand_key,
                pend_ind=pend,
                ver_active=jnp.where(can, True, proto["ver_active"]),
                ver_done_t=jnp.where(can, t + proto["pairing"], proto["ver_done_t"]),
                ver_level=jnp.where(can, best_level, proto["ver_level"]),
                ver_rel=jnp.where(can, best_rel, proto["ver_rel"]),
                ver_pk=jnp.where(can, best_pk, proto["ver_pk"]),
                ver_single=jnp.where(can, sel_single, proto["ver_single"]),
                ver_sig=ver_sig,
                sig_checked=proto["sig_checked"] + can.astype(jnp.int32),
            )
        )
        return state

    # -- engine hooks --------------------------------------------------------
    def tick(self, net, state):
        state = self._channel_deliver(net, state)
        state = self._commit(net, state)
        state = self._dissemination(net, state)
        state = self._select(net, state)
        return state

    def all_done(self, state):
        live = ~state.down
        return jnp.all(jnp.where(live, state.done_at > 0, True))


def make_gsf(
    params: Optional[GSFSignatureParameters] = None,
    capacity: int = 8,  # generic ring unused by this protocol
    seed: int = 0,
):
    """Host-side construction mirroring GSFSignature.init (gsf.py:init):
    same JavaRandom stream for node building and the down-node draw."""
    params = params or GSFSignatureParameters()
    n = params.node_count
    nb = registry_node_builders.get_by_name(params.node_builder_name)
    latency = registry_network_latencies.get_by_name(params.network_latency_name)
    rd = JavaRandom(0)

    nodes = [Node(rd, nb) for _ in range(n)]
    down = np.zeros(n, dtype=bool)
    set_down = 0
    while set_down < params.nodes_down:
        i = rd.next_int(n)
        if not down[i] and i != 1:
            # node 1 kept up to help debugging (GSFSignature.java:621)
            down[i] = True
            set_down += 1

    pairing = np.maximum(
        1, (params.pairing_time * np.array([nd.speed_ratio for nd in nodes]))
    ).astype(np.int32)

    proto = BatchedGSF(params)
    city_index = getattr(latency, "city_index", None)
    cols = build_node_columns(nodes, city_index)
    net = BatchedNetwork(proto, latency, n, capacity=capacity)
    state = net.init_state(
        cols,
        seed=seed,
        proto=proto.proto_init(n, pairing),
        down=down,
    )
    return net, state

"""Batched GSFSignature: north-star config #2 on the TPU engine.

Re-expression of protocols/GSFSignature.java (via the oracle port
protocols/gsf.py) on the shared bitset-aggregation machinery
(_agg_batched.BitsetAggBase): XOR-relative packed bitsets, per-level
channel slots + freshest-offer backstop, and a one-slot verification
register committing at t + pairingTime.  Like batched Handel, every
per-level computation runs once per width BUCKET on a stacked level
axis, and the per-level send loops (dissemination, accelerated calls)
collapse into single stacked sends — the r4 program-size rewrite.

GSF specifics vs Handel:

  * a node's level-l sends carry its whole *completed prefix* — the union
    of consecutively complete levels is always the interval [0, 2^k) in
    the XOR layout (getLastFinishedLevel, GSFSignature.java:376-392), so
    the multi-level payload is transmitted as the level-confined content
    (w_l words) plus ONE integer k per message (`in_aux`/`cand_pk`); the
    receiver reconstructs the interval exactly, which is what drives the
    absorb-lower-levels path of updateVerifiedSignatures (:397-411).
  * level sends are budgeted: remainingCalls starts at the level size and
    is reset on improvement (:345-356, :438-443); dissemination stops
    when the budget is exhausted rather than cycling forever.
  * verification candidates are scored with evaluateSig (:478-520):
    completion bonus 1_000_000 - 10*level, otherwise 100_000 - 100*level
    + addedSigs, individual-sig fallback score 1 — and the *global* best
    across levels is verified (no per-level uniform choice, :524-558).
  * every first message from a sender enqueues that sender's individual
    single-bit signature as a separate verification candidate
    (onNewSig, :560-577), tracked here as pending/seen bitsets with the
    lowest-index pending bit as the level's representative candidate.
  * accelerated calls: on improvement, burst the completed prefix to
    acceleratedCallsCount fresh peers of each level the prefix now covers
    (:438-451).
  * no Byzantine attack modes, no desynchronized start, no blacklist
    (nodes can only be down); done nodes keep verifying their queues.

Distribution-parity approximations (as in batched Handel): counter-hash
emission order instead of the shuffled peer lists, channel displacement
instead of an unbounded queue (top-K score-curated candidates), send-time
receiver counters, simultaneous same-ms deliveries.
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
import numpy as np
from jax import lax

from ..core.node import Node, build_node_columns
from ..core.registries import registry_network_latencies, registry_node_builders
from ..engine import BatchedNetwork
from ..engine.rng import hash32
from ..ops.bitops import block_mask, popcount_words
from ..utils.javarand import JavaRandom
from ._agg_batched import INT32_MAX, BitsetAggBase
from .gsf import GSFSignatureParameters


class BatchedGSF(BitsetAggBase):
    CAND_SLOTS = 8  # K: score-curated verification candidates per level

    def __init__(self, params: GSFSignatureParameters):
        self.params = params
        self._init_geometry(params.node_count)
        # prefix interval masks: pref_masks[k] = bits [0, 2^k)
        self.pref_masks = np.stack(
            [block_mask(0, 1 << k, self.n_words) for k in range(self.n_levels)]
        )

    def msg_size(self, mtype: int) -> int:
        # Size = level byte + bit field + the aggregated sig + our own sig
        # (SendSigs, GSFSignature.java:143-164)
        expected = 1 if mtype == 0 else 1 << (mtype - 1)
        return 1 + expected // 8 + 96

    # -- state ---------------------------------------------------------------
    def proto_init(self, n_nodes: int, pairing: np.ndarray):
        n, L, K = self.n_nodes, self.n_levels, self.CAND_SLOTS
        own = np.zeros((n, self.n_words), dtype=np.uint32)
        own[:, 0] = 1  # bit 0 = own signature (level 0)
        in_key, in_sigs = self._channel_init(n)
        ss = self.CHANNEL_DEPTH + 1
        cand_sigs = {
            f"cand_sig{i}": jnp.zeros((n, b.nl * K * b.w_pad), jnp.uint32)
            for i, b in enumerate(self.buckets)
        }
        remaining = np.zeros((n, L), dtype=np.int32)
        for l in range(1, L):
            remaining[:, l] = 1 << (l - 1)
        return {
            "ver": jnp.asarray(own),  # verified union, per level blocks
            "indiv": jnp.zeros((n, self.n_words), jnp.uint32),
            "ind_seen": jnp.zeros((n, self.n_words), jnp.uint32),
            "pend_ind": jnp.zeros((n, self.n_words), jnp.uint32),
            "in_key": in_key,
            **in_sigs,
            "displaced": jnp.int32(0),
            "in_aux": jnp.zeros((n, (L - 1) * ss), jnp.int32),  # prefix k
            "cand_key": jnp.full((n, (L - 1) * K), INT32_MAX, jnp.int32),  # rel
            "cand_pk": jnp.zeros((n, (L - 1) * K), jnp.int32),
            **cand_sigs,
            "ver_active": jnp.zeros(n, bool),
            "ver_done_t": jnp.zeros(n, jnp.int32),
            "ver_level": jnp.zeros(n, jnp.int32),
            "ver_rel": jnp.zeros(n, jnp.int32),
            "ver_pk": jnp.zeros(n, jnp.int32),
            "ver_single": jnp.zeros(n, bool),  # individual-sig verification
            "ver_sig": jnp.zeros((n, self.w_max), jnp.uint32),
            "remaining": jnp.asarray(remaining),
            "pos": jnp.zeros((n, L), jnp.int32),
            "sig_checked": jnp.zeros(n, jnp.int32),
            "pairing": jnp.asarray(pairing, jnp.int32),
        }

    # -- helpers -------------------------------------------------------------
    def _prefix_k(self, ver):
        """Number of consecutively complete levels from level 1 up
        (getLastFinishedLevel): the verified union is then >= [0, 2^k)."""
        if self.n_levels == 1:
            return jnp.zeros(ver.shape[0], jnp.int32)
        comp = self._level_stats(
            [
                popcount_words(self._blocks(ver, b))
                == jnp.asarray([self.bs[l] for l in b.levels], jnp.int32)[None, :]
                for b in self.buckets
            ]
        )
        return jnp.sum(jnp.cumprod(comp.astype(jnp.int32), axis=1), axis=1)

    def _eval_sig(self, sig, vb, ib, bs, lv):
        """evaluateSig (GSFSignature.java:478-520), broadcast-generic:
        sig/vb/ib are [..., w] (broadcastable against each other), bs/lv
        broadcast against the popcount shapes."""
        ver_card = popcount_words(vb)
        sig_card = popcount_words(sig)
        inter = popcount_words(sig & vb) > 0
        with_ind = sig | ib
        with_ind_v = with_ind | vb
        new_total = jnp.where(
            ver_card == 0,
            sig_card,
            jnp.where(inter, popcount_words(with_ind), popcount_words(with_ind_v)),
        )
        added = jnp.where(ver_card == 0, sig_card, new_total - ver_card)
        indiv_fallback = (
            (sig_card == 1) & (popcount_words(sig & ib) == 0)
        ).astype(jnp.int32)
        score = jnp.where(
            added <= 0,
            indiv_fallback,
            jnp.where(
                new_total == bs,
                1_000_000 - lv * 10,
                100_000 - lv * 100 + added,
            ),
        )
        return jnp.where(ver_card >= bs, 0, score)

    def _bs_arr(self, b):
        return jnp.asarray([self.bs[l] for l in b.levels], jnp.int32)

    # -- tick phase 1: commit due verifications ------------------------------
    def _commit(self, net, state):
        """updateVerifiedSignatures (GSFSignature.java:379-460), stacked."""
        p = self.params
        proto = state.proto
        t = state.time
        n, L = self.n_nodes, self.n_levels
        ids = jnp.arange(n, dtype=jnp.int32)
        lv_all = jnp.arange(1, L, dtype=jnp.int32)
        bs_all = jnp.asarray(self.lv_bs)

        due = proto["ver_active"] & (t >= proto["ver_done_t"])
        ver, indiv = proto["ver"], proto["indiv"]
        remaining = proto["remaining"]
        rel = proto["ver_rel"]
        pk = proto["ver_pk"]
        lvl = proto["ver_level"]

        # absorb the completed prefix (:397-411) at full width first: the
        # sender's consecutive-complete levels cover [0, 2^pk), which
        # includes the committed block and the receiver's levels 1..pk
        absorb = due & (pk >= lvl)
        interval = jnp.asarray(self.pref_masks)[jnp.clip(pk, 0, L - 1)]
        newly = popcount_words(interval & ~ver) > 0
        reset_r = absorb & newly
        ver_a = jnp.where(absorb[:, None], ver | interval, ver)

        improved_any = jnp.zeros(n, bool)
        ver_pieces, indiv_pieces = [], []
        for i, b in enumerate(self.buckets):
            lv = jnp.asarray(b.levels, jnp.int32)
            bs = self._bs_arr(b)
            m = due[:, None] & (lvl[:, None] == lv[None, :])  # [N, nl]
            r0 = rel[:, None] & (bs[None, :] - 1)
            sig_b = proto["ver_sig"][:, None, : b.w_pad]
            ver_b = self._blocks(ver_a, b)  # post-absorb ("may now be complete")
            indiv_b = self._blocks(indiv, b)

            # individual sig: set the indiv bit first (:383-385)
            single = m & proto["ver_single"][:, None]
            oh = self._onehot(r0, b.w_pad)
            new_indiv_b = jnp.where(single[..., None], indiv_b | oh, indiv_b)
            # holder.sigs |= indivVerifiedSig (:386)
            sigs = sig_b | new_indiv_b

            # absorbed commits act as a full block at the committed level
            full_block = jnp.asarray(
                np.stack(
                    [
                        np.asarray(
                            [
                                0xFFFFFFFF
                                if (j + 1) * 32 <= self.bs[l]
                                else ((1 << (self.bs[l] % 32)) - 1 if j * 32 < self.bs[l] else 0)
                                for j in range(b.w_pad)
                            ],
                            np.uint32,
                        )
                        for l in b.levels
                    ]
                )
            )
            sigs = jnp.where(
                (m & absorb[:, None])[..., None], full_block[None, :, :], sigs
            )

            # disjoint sets aggregate (:413-417)
            disjoint = (popcount_words(ver_b) > 0) & (
                popcount_words(sigs & ver_b) == 0
            )
            sigs = jnp.where((m & disjoint)[..., None], sigs | ver_b, sigs)

            # replacement on improvement (:419-431)
            improve = m & (
                (popcount_words(sigs) > popcount_words(ver_b))
                | reset_r[:, None]
            )
            ver_pieces.append(jnp.where(improve[..., None], sigs, ver_b))
            indiv_pieces.append(jnp.where(m[..., None], new_indiv_b, indiv_b))
            improved_any = improved_any | jnp.any(improve, axis=1)

        ver = self._assemble(ver_a, ver_pieces)
        indiv = self._assemble(indiv, indiv_pieces)

        # reset send budgets for levels >= the committed level (:421-423)
        lv_idx = jnp.arange(L, dtype=jnp.int32)[None, :]
        sizes = jnp.asarray([0] + [1 << (j - 1) for j in range(1, L)], jnp.int32)
        remaining = jnp.where(
            improved_any[:, None] & (lv_idx >= lvl[:, None]), sizes[None, :], remaining
        )

        state = state._replace(
            proto=dict(proto, ver=ver, indiv=indiv, remaining=remaining)
        )

        # accelerated calls (:438-451): after the merges, burst the
        # completed prefix to fresh peers of each level it now covers.
        # Each node committed at exactly one level (ver_level); burst at
        # level mm iff the commit improved, mm > committed level, and the
        # new prefix k reaches mm-1.  One stacked send over [N, L-1, acc].
        if p.accelerated_calls_count > 0 and L > 2:
            k_new = self._prefix_k(ver)
            acc = p.accelerated_calls_count
            havings = ver | jnp.asarray(self.pref_masks)[jnp.clip(k_new, 0, L - 1)]
            fan = jnp.minimum(jnp.int32(acc), bs_all)  # [L-1]
            burst = (
                improved_any[:, None]
                & (lvl[:, None] < lv_all[None, :])
                & (k_new[:, None] >= lv_all[None, :] - 1)
                & (lv_all[None, :] >= 2)
            )  # [N, L-1]
            take = jnp.where(
                burst,
                jnp.minimum(jnp.maximum(remaining[:, 1:], 0), fan[None, :]),
                0,
            )
            remaining = remaining.at[:, 1:].add(-take)
            state = state._replace(proto=dict(state.proto, remaining=remaining))

            ks = jnp.arange(acc, dtype=jnp.int32)
            offset = hash32(state.seed, ids[:, None], lv_all[None, :], t) & (
                bs_all[None, :] - 1
            )  # [N, L-1]
            relb = bs_all[None, :, None] + (
                (proto["pos"][:, 1:, None] + offset[:, :, None] + ks[None, None, :])
                & (bs_all[None, :, None] - 1)
            )  # [N, L-1, acc]
            mask_b = ks[None, None, :] < take[:, :, None]
            content = []
            for b in self.buckets:
                lows = self._lows(havings, b)  # [N, nl, w_pad]
                full = jnp.zeros((n, L - 1, b.w_pad), jnp.uint32)
                full = full.at[:, b.lo - 1 : b.hi, :].set(lows)
                content.append(
                    jnp.broadcast_to(
                        full[:, :, None, :], (n, L - 1, acc, b.w_pad)
                    ).reshape(n * (L - 1) * acc, b.w_pad)
                )
            state = self._send_stacked(
                net,
                state,
                mask_b.reshape(-1),
                jnp.repeat(ids, (L - 1) * acc),
                (ids[:, None, None] ^ relb).reshape(-1),
                jnp.broadcast_to(lv_all[None, :, None], (n, L - 1, acc)).reshape(-1),
                content,
                aux=jnp.repeat(k_new, (L - 1) * acc),
            )

        proto = state.proto
        total = popcount_words(proto["ver"])
        done_now = (
            improved_any & (state.done_at == 0) & ~state.down & (total >= p.threshold)
        )
        state = state._replace(
            done_at=jnp.where(done_now, t, state.done_at),
            proto=dict(proto, ver_active=proto["ver_active"] & ~due),
        )
        return state

    # -- tick phase 2: deliver channel slots into candidates -----------------
    def _channel_deliver(self, net, state):
        """onNewSig (GSFSignature.java:560-577): enqueue the aggregate and,
        once per sender, its individual signature."""
        proto = state.proto
        n, L, K = self.n_nodes, self.n_levels, self.CAND_SLOTS
        rel_mask = (1 << self.rel_bits) - 1
        ss = self.CHANNEL_DEPTH + 1

        in_key, due_all, empty_tpl = self._advance_channel(
            proto["in_key"], state.time
        )
        keys3 = self._keys_stacked(in_key)
        due3 = due_all.reshape(n, L - 1, ss)
        # only arrival slot (t mod D) and the fresh slot can be due at t
        keys2, due2 = self._due_pair_keys(keys3, due3, state.time)
        rel2 = keys2 & rel_mask
        pk3 = proto["in_aux"].reshape(n, L - 1, ss)
        pk2, _ = self._due_pair_keys(pk3, due3, state.time)

        ver, indiv = proto["ver"], proto["indiv"]
        seen, pend = proto["ind_seen"], proto["pend_ind"]

        key_pieces, pk_pieces = [], []
        cand_sig_updates = {}
        seen_pieces, pend_pieces = [], []
        for i, b in enumerate(self.buckets):
            sl = slice(b.lo - 1, b.hi)
            lv = jnp.asarray(b.levels, jnp.int32)
            bs = self._bs_arr(b)
            due = due2[:, sl, :]
            rel = rel2[:, sl, :]
            r0 = rel & (bs[None, :, None] - 1)
            sig_new = self._due_pair_sig(proto, i, state.time)  # [N, nl, 2, w_pad]
            pk_new = pk2[:, sl, :]

            # individual sig enqueue: once per sender per level — the bit
            # lives in the level block, so track it block-locally and
            # reassemble (no full-width onehot per slot)
            oh = jnp.where(
                due[..., None], self._onehot(r0, b.w_pad), jnp.uint32(0)
            )  # [N, nl, 2, w_pad]
            arrived_bits = jnp.bitwise_or.reduce(oh, axis=2)  # [N, nl, w_pad]
            seen_b = self._blocks(seen, b)
            pend_b = self._blocks(pend, b)
            fresh = arrived_bits & ~seen_b
            seen_pieces.append(seen_b | fresh)
            pend_pieces.append(pend_b | fresh)

            # merge [K existing + 2 new] candidates, keep top-K by score
            c_key = proto["cand_key"].reshape(n, L - 1, K)[:, sl, :]
            c_pk = proto["cand_pk"].reshape(n, L - 1, K)[:, sl, :]
            c_sig = self._sig_view(proto, i, K, prefix="cand_sig")

            all_key = jnp.concatenate(
                [c_key, jnp.where(due, rel, INT32_MAX)], axis=2
            )
            all_pk = jnp.concatenate([c_pk, pk_new], axis=2)
            all_sig = jnp.concatenate([c_sig, sig_new], axis=2)
            valid = all_key != INT32_MAX

            ver_b = self._blocks(ver, b)
            indiv_b = self._blocks(indiv, b)
            # prefix-carrying candidates are full-block in this level, so
            # the exact evaluateSig on block content scores them correctly
            score = self._eval_sig(
                all_sig,
                ver_b[:, :, None, :],
                indiv_b[:, :, None, :],
                bs[None, :, None],
                lv[None, :, None],
            )
            score = jnp.where(valid, score, -1)
            # drop worthless entries (checkSigs' iterator remove, :532-537)
            score = jnp.where(score == 0, -1, score)

            order = jnp.argsort(-score, axis=2)[:, :, :K]
            top_ok = jnp.take_along_axis(score, order, axis=2) > 0
            sel_key = jnp.where(
                top_ok, jnp.take_along_axis(all_key, order, axis=2), INT32_MAX
            )
            sel_pk = jnp.take_along_axis(all_pk, order, axis=2)
            sel_sig = jnp.take_along_axis(all_sig, order[..., None], axis=2)

            key_pieces.append(sel_key)
            pk_pieces.append(sel_pk)
            cand_sig_updates[f"cand_sig{i}"] = sel_sig.reshape(n, b.nl * K * b.w_pad)

        state = state._replace(
            proto=dict(
                proto,
                in_key=jnp.where(due_all, empty_tpl[None, :], in_key),
                cand_key=jnp.concatenate(key_pieces, axis=1).reshape(n, (L - 1) * K),
                cand_pk=jnp.concatenate(pk_pieces, axis=1).reshape(n, (L - 1) * K),
                pend_ind=self._assemble(pend, pend_pieces),
                ind_seen=self._assemble(seen, seen_pieces),
                **cand_sig_updates,
            )
        )
        return state

    # -- tick phase 3: periodic dissemination --------------------------------
    def _dissemination(self, net, state):
        """doCycle over started levels with send budgets
        (GSFSignature.java:289-343), all levels in ONE stacked send."""
        p = self.params
        proto = state.proto
        t = state.time
        n, L = self.n_nodes, self.n_levels
        ids = jnp.arange(n, dtype=jnp.int32)
        lv_all = jnp.arange(1, L, dtype=jnp.int32)
        bs_all = jnp.asarray(self.lv_bs)

        on_beat = (t >= 1) & (lax.rem(t - 1, jnp.int32(p.period_duration_ms)) == 0)
        may_send = on_beat & ~state.down

        k = self._prefix_k(proto["ver"])
        havings = proto["ver"] | jnp.asarray(self.pref_masks)[
            jnp.clip(k, 0, L - 1)
        ]
        complete = self._level_stats(
            [
                popcount_words(self._lows(havings, b)) >= self._bs_arr(b)[None, :]
                for b in self.buckets
            ]
        )
        started = (t >= lv_all[None, :] * jnp.int32(p.timeout_per_level_ms)) | complete
        remaining = proto["remaining"][:, 1:]
        mask = may_send[:, None] & started & (remaining > 0)  # [N, L-1]

        offset = hash32(state.seed, ids[:, None], lv_all[None, :]) & (
            bs_all[None, :] - 1
        )
        pos = proto["pos"][:, 1:]
        rel = (bs_all[None, :] + ((pos + offset) & (bs_all[None, :] - 1))).astype(
            jnp.int32
        )
        new_pos = proto["pos"].at[:, 1:].set(jnp.where(mask, pos + 1, pos))
        new_remaining = proto["remaining"].at[:, 1:].add(-mask.astype(jnp.int32))
        state = state._replace(
            proto=dict(proto, pos=new_pos, remaining=new_remaining)
        )

        content = []
        for b in self.buckets:
            lows = self._lows(havings, b)
            full = jnp.zeros((n, L - 1, b.w_pad), jnp.uint32)
            full = full.at[:, b.lo - 1 : b.hi, :].set(lows)
            content.append(full.reshape(n * (L - 1), b.w_pad))

        state = self._send_stacked(
            net,
            state,
            mask.reshape(-1),
            jnp.repeat(ids, L - 1),
            (ids[:, None] ^ rel).reshape(-1),
            jnp.broadcast_to(lv_all[None, :], (n, L - 1)).reshape(-1),
            content,
            aux=jnp.repeat(k, L - 1),
        )
        return state

    # -- tick phase 4: start verifications (checkSigs) -----------------------
    def _select(self, net, state, view=None):
        """Global best-scored candidate across levels
        (GSFSignature.java:524-558).

        `view` (tick() passes it) holds the BOUNDARY state — candidates,
        pending individuals and aggregates as of the end of the previous
        tick — matching the reference's boundary-fired checkSigs
        conditional task (GSFSignature.java:631-632, Network.java:533-565;
        same mechanism as handel_batched._select).  Write-backs are
        compare-and-clear (on the sender-rel key) / bit-clear merges.
        Write-backs target the viewed entry by (key, cardinality)
        identity matched against any current slot of the level — see the
        equivalent handel_batched._select note."""
        proto = state.proto
        v = proto if view is None else {**proto, **view}
        t = state.time
        n, L, K = self.n_nodes, self.n_levels, self.CAND_SLOTS
        ids = jnp.arange(n, dtype=jnp.int32)

        free = ~proto["ver_active"] & ~state.down & (t >= 1)
        ver, indiv, pend = v["ver"], v["indiv"], v["pend_ind"]

        score_p, rel_p, pk_p, kidx_p = [], [], [], []
        key_pieces, pend_pieces, vcard_pieces, ccard_pieces = [], [], [], []
        for i, b in enumerate(self.buckets):
            sl = slice(b.lo - 1, b.hi)
            lv = jnp.asarray(b.levels, jnp.int32)
            bs = self._bs_arr(b)
            c_key = v["cand_key"].reshape(n, L - 1, K)[:, sl, :]
            c_pk = v["cand_pk"].reshape(n, L - 1, K)[:, sl, :]
            c_sig = self._sig_view(v, i, K, prefix="cand_sig")
            valid = c_key != INT32_MAX
            ver_b = self._blocks(ver, b)
            indiv_b = self._blocks(indiv, b)
            score = self._eval_sig(
                c_sig,
                ver_b[:, :, None, :],
                indiv_b[:, :, None, :],
                bs[None, :, None],
                lv[None, :, None],
            )
            score = jnp.where(valid, score, -1)
            # curation: drop worthless entries permanently (condemn mask,
            # applied by entry identity below)
            key_pieces.append(valid & (score == 0))
            vcard_pieces.append(popcount_words(c_sig))
            cur_sig = self._sig_view(proto, i, K, prefix="cand_sig")
            ccard_pieces.append(popcount_words(cur_sig))
            kbest = jnp.argmax(score, axis=2)
            sbest = jnp.take_along_axis(score, kbest[..., None], axis=2)[..., 0]

            # individual pending representative: lowest pending bit
            pend_b = self._blocks(pend, b)
            has_pend = popcount_words(pend_b) > 0
            m_ind = self._lowest_bit(pend_b)
            oh = self._onehot(m_ind & (bs[None, :] - 1), b.w_pad)
            s_ind = self._eval_sig(
                oh, ver_b, indiv_b, bs[None, :], lv[None, :]
            )
            s_ind = jnp.where(has_pend, s_ind, -1)
            # worthless individuals are dropped too
            pend_pieces.append(
                jnp.where(
                    (has_pend & (s_ind == 0))[..., None], pend_b & ~oh, pend_b
                )
            )

            use_ind = s_ind > sbest
            score_p.append(jnp.maximum(sbest, s_ind))
            rel_p.append(
                jnp.where(
                    use_ind,
                    bs[None, :] + (m_ind & (bs[None, :] - 1)),
                    jnp.take_along_axis(c_key, kbest[..., None], axis=2)[..., 0],
                )
            )
            pk_p.append(
                jnp.where(
                    use_ind,
                    0,
                    jnp.take_along_axis(c_pk, kbest[..., None], axis=2)[..., 0],
                )
            )
            kidx_p.append(jnp.where(use_ind, -1, kbest))

        l_score = self._level_stats(score_p)  # [N, L-1]
        l_rel = self._level_stats(rel_p)
        l_pk = self._level_stats(pk_p)
        l_kidx = self._level_stats(kidx_p)
        # pend writes are pure bit-CLEARS on the view: merge as a clear
        # mask onto the current array (a bit deliver(t) set stays set)
        pend_after_view = self._assemble(pend, pend_pieces)
        pend_clear = v["pend_ind"] & ~pend_after_view
        pend = proto["pend_ind"] & ~pend_clear
        # curation removal by (key, cardinality) ENTRY IDENTITY matched
        # against any current slot of the level (the key alone is only the
        # sender rel; a same-sender refresh differs in cardinality — see
        # the handel_batched note)
        condemn3 = jnp.concatenate(key_pieces, axis=1)  # [N, L-1, K]
        vkey3 = v["cand_key"].reshape(n, L - 1, K)
        vcard3 = jnp.concatenate(vcard_pieces, axis=1)
        ckey3 = proto["cand_key"].reshape(n, L - 1, K)
        ccard3 = jnp.concatenate(ccard_pieces, axis=1)
        cleared = self._entry_clear(ckey3, ccard3, vkey3, vcard3, condemn3)
        new_key3 = jnp.where(cleared, INT32_MAX, ckey3)

        # global best across levels; ascending-level iteration with strict >
        # in the original = first maximum wins = argmax
        lidx = jnp.argmax(l_score, axis=1)
        best_score = jnp.take_along_axis(l_score, lidx[:, None], axis=1)[:, 0]
        best_level = (lidx + 1).astype(jnp.int32)
        best_rel = jnp.take_along_axis(l_rel, lidx[:, None], axis=1)[:, 0]
        best_pk = jnp.take_along_axis(l_pk, lidx[:, None], axis=1)[:, 0]
        best_kidx = jnp.take_along_axis(l_kidx, lidx[:, None], axis=1)[:, 0]

        can = free & (best_score > 0)
        sel_single = best_kidx < 0

        # load the chosen sig into the verification register
        bs_sel = jnp.asarray(self.lv_bs)[jnp.maximum(best_level - 1, 0)]
        ver_sig = proto["ver_sig"]
        for i, b in enumerate(self.buckets):
            m = can & (best_level >= b.lo) & (best_level <= b.hi)
            c_sig = self._sig_view(v, i, K, prefix="cand_sig")
            li = jnp.clip(best_level - b.lo, 0, b.nl - 1)
            c_lv = jnp.take_along_axis(c_sig, li[:, None, None, None], axis=1)[:, 0]
            safe_k = jnp.maximum(best_kidx, 0)
            from_buf = jnp.take_along_axis(c_lv, safe_k[:, None, None], axis=1)[:, 0]
            single = self._onehot(best_rel & (bs_sel - 1), b.w_pad)
            sig_l = jnp.where(sel_single[:, None], single, from_buf)
            pad = jnp.zeros((n, self.w_max - b.w_pad), jnp.uint32)
            ver_sig = jnp.where(
                m[:, None], jnp.concatenate([sig_l, pad], axis=1), ver_sig
            )

        # clear the individual pending bit on selection (bit best_rel of the
        # full-width rel-space vector)
        oh_full = self._onehot(best_rel, self.n_words)
        pend = jnp.where((can & sel_single)[:, None], pend & ~oh_full, pend)

        # remove the chosen buffer candidate by (key, cardinality) entry
        # identity against the chosen level's CURRENT slots
        lvl_idx = jnp.maximum(best_level - 1, 0)
        sel_card = jnp.take_along_axis(
            jnp.take_along_axis(vcard3, lvl_idx[:, None, None], axis=1)[:, 0],
            jnp.maximum(best_kidx, 0)[:, None],
            axis=1,
        )[:, 0]
        remove = can & ~sel_single
        new_key3 = self._remove_chosen(
            ids, new_key3, ccard3, lvl_idx, best_rel, sel_card, remove
        )
        new_cand_key = new_key3.reshape(n, (L - 1) * K)

        state = state._replace(
            proto=dict(
                proto,
                cand_key=new_cand_key,
                pend_ind=pend,
                ver_active=jnp.where(can, True, proto["ver_active"]),
                ver_done_t=jnp.where(can, t + proto["pairing"], proto["ver_done_t"]),
                ver_level=jnp.where(can, best_level, proto["ver_level"]),
                ver_rel=jnp.where(can, best_rel, proto["ver_rel"]),
                ver_pk=jnp.where(can, best_pk, proto["ver_pk"]),
                ver_single=jnp.where(can, sel_single, proto["ver_single"]),
                ver_sig=ver_sig,
                sig_checked=proto["sig_checked"] + can.astype(jnp.int32),
            )
        )
        return state

    # -- engine hooks --------------------------------------------------------
    def tick(self, net, state):
        # boundary-view selection, like handel_batched.tick: checkSigs is
        # a conditional task fired at the ms boundary, so it sees
        # candidates/pending/aggregates as of the END of the previous tick
        pre_cand = {
            k: state.proto[k]
            for k in ("cand_key", "cand_pk", "pend_ind")
            + tuple(f"cand_sig{i}" for i in range(len(self.buckets)))
        }
        state = self._channel_deliver(net, state)
        pre_merge = {k: state.proto[k] for k in ("ver", "indiv")}
        state = self._commit(net, state)
        state = self._select(net, state, view={**pre_cand, **pre_merge})
        return state

    def all_done(self, state):
        live = ~state.down
        return jnp.all(jnp.where(live, state.done_at > 0, True))


def make_gsf(
    params: Optional[GSFSignatureParameters] = None,
    capacity: int = 8,  # generic ring unused by this protocol
    seed: int = 0,
):
    """Host-side construction mirroring GSFSignature.init (gsf.py:init):
    same JavaRandom stream for node building and the down-node draw."""
    params = params or GSFSignatureParameters()
    n = params.node_count
    nb = registry_node_builders.get_by_name(params.node_builder_name)
    latency = registry_network_latencies.get_by_name(params.network_latency_name)
    rd = JavaRandom(0)

    nodes = [Node(rd, nb) for _ in range(n)]
    down = np.zeros(n, dtype=bool)
    set_down = 0
    while set_down < params.nodes_down:
        i = rd.next_int(n)
        if not down[i] and i != 1:
            # node 1 kept up to help debugging (GSFSignature.java:621)
            down[i] = True
            set_down += 1

    pairing = np.maximum(
        1, (params.pairing_time * np.array([nd.speed_ratio for nd in nodes]))
    ).astype(np.int32)

    proto = BatchedGSF(params)
    # dissemination fires at t >= 1 with (t - 1) % period == 0
    proto.BEAT_PERIOD = params.period_duration_ms
    proto.BEAT_RESIDUES = (1 % params.period_duration_ms,)
    city_index = getattr(latency, "city_index", None)
    cols = build_node_columns(nodes, city_index)
    # flat mode: aggregation messaging bypasses the generic store entirely
    # (the channel in _agg_batched), so keep the per-tick scan minimal
    net = BatchedNetwork(proto, latency, n, capacity=capacity, wheel_rows=0)
    state = net.init_state(
        cols,
        seed=seed,
        proto=proto.proto_init(n, pairing),
        down=down,
    )
    return net, state

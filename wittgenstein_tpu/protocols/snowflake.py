"""Snowflake: Slush plus a confidence counter B — a node accepts once it has
seen B consecutive successful same-color majorities.

Reference semantics: protocols/Snowflake.java (counter reset on flip
:170-188; shared machinery in `_avalanche`).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

from ..core.params import WParameters, register_protocol
from ..core.registries import registry_network_latencies, registry_node_builders
from ..oracle.network import Network, Protocol
from ._avalanche import AvalancheNode, color_play, init_two_colors


@dataclasses.dataclass
class SnowflakeParameters(WParameters):
    nodes_av: int = 100
    m: int = 4
    k: int = 7
    a: float = 4.0
    b: int = 7
    node_builder_name: Optional[str] = None
    network_latency_name: Optional[str] = None

    @property
    def ak(self) -> float:
        return self.a * self.k


class SnowflakeNode(AvalancheNode):
    __slots__ = ("cnt",)

    def __init__(self, p: "Snowflake"):
        super().__init__(p)
        self.cnt = 0

    def on_answer(self, query_id: int, color: int) -> None:
        """Snowflake loop (Snowflake.java:170-188): flip and reset cnt on an
        opposing majority, increment cnt on a confirming one; keep querying
        while cnt <= B."""
        p = self._p
        asw = self.answer_ip[query_id]
        asw.colors_found[color] += 1
        if asw.answer_count() == p.params.k:
            del self.answer_ip[query_id]
            if asw.colors_found[self._other_color()] > p.params.ak:
                self.my_color = self._other_color()
                self.cnt = 0
            elif asw.colors_found[self.my_color] > p.params.ak:
                self.cnt += 1
            if self.cnt <= p.params.b:
                self.send_query(asw.round + 1)


@register_protocol("Snowflake", SnowflakeParameters)
class Snowflake(Protocol):
    def __init__(self, params: SnowflakeParameters):
        self.params = params
        self._network: Network[SnowflakeNode] = Network()
        self.nb = registry_node_builders.get_by_name(params.node_builder_name)
        self._network.set_network_latency(
            registry_network_latencies.get_by_name(params.network_latency_name)
        )

    def init(self) -> None:
        init_two_colors(self, SnowflakeNode)

    def network(self) -> Network:
        return self._network

    def copy(self) -> "Snowflake":
        return Snowflake(self.params)

    def __str__(self) -> str:
        return (
            f"Snowflake{{nodes={self.params.nodes_av}, "
            f"latency={self._network.network_latency}, M={self.params.m}, "
            f"AK={self.params.ak}, B={self.params.b}}}"
        )

    def play(self, graph_path: Optional[str] = None, verbose: bool = False):
        """Scenario driver (Snowflake.java:234-282)."""
        b = self.params.b
        return color_play(self, lambda gn: gn.cnt < b, graph_path, verbose)


def main():
    Snowflake(SnowflakeParameters(100, 5, 7, 4.0 / 7.0, 3, None, None)).play(
        graph_path="graph.png", verbose=True
    )


if __name__ == "__main__":
    main()

"""PingPong: the sample protocol — a witness Pings everyone, nodes Pong back.

Reference semantics: protocols/PingPong.java.  Canonical first target for
both engines: the oracle run is the golden sequence, the batched engine must
match it distributionally.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

from ..core.node import Node
from ..core.params import WParameters, register_protocol
from ..core.registries import registry_network_latencies, registry_node_builders
from ..oracle.messages import Message
from ..oracle.network import Network, Protocol


@dataclasses.dataclass
class PingPongParameters(WParameters):
    node_ct: int = 1000
    node_builder_name: Optional[str] = None
    network_latency_name: Optional[str] = None


class Ping(Message):
    def action(self, network, from_node, to_node):
        to_node.on_ping(from_node)


class Pong(Message):
    def action(self, network, from_node, to_node):
        to_node.on_pong()


class PingPongNode(Node):
    __slots__ = ("pong", "_net")

    def __init__(self, network, nb):
        super().__init__(network.rd, nb)
        self.pong = 0
        self._net = network

    def on_ping(self, from_node):
        self._net.send(Pong(), self, from_node)

    def on_pong(self):
        self.pong += 1


@register_protocol("PingPong", PingPongParameters)
class PingPong(Protocol):
    def __init__(self, params: PingPongParameters):
        self.params = params
        self.nb = registry_node_builders.get_by_name(params.node_builder_name)
        self._network: Network[PingPongNode] = Network()
        self._network.set_network_latency(
            registry_network_latencies.get_by_name(params.network_latency_name)
        )

    def copy(self) -> "PingPong":
        return PingPong(self.params)

    def init(self) -> None:
        for _ in range(self.params.node_ct):
            self._network.add_node(PingPongNode(self._network, self.nb))
        self._network.send_all(Ping(), self._network.get_node_by_id(0))

    def network(self) -> Network:
        return self._network


def main():
    p = PingPong(PingPongParameters())
    p.init()
    for i in range(0, 500, 50):
        print(f"{i} ms, pongs received {p.network().get_node_by_id(0).pong}")
        p.network().run_ms(50)


if __name__ == "__main__":
    main()

"""SanFerminSignature: binomial-tree pairwise BLS aggregation — each node
swaps aggregate signatures with counterpart sets of decreasing common binary
prefix, O(log n) contacts per node.

Reference semantics: protocols/SanFerminSignature.java (swap request/reply
state machine :229-323, timeout re-picks :329-369, goNextLevel level descent
:379-419, pairing-time verification via registerTask :434-455).
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Dict, List, Optional, Set

from ..core import stats as SH
from ..core.params import WParameters, register_protocol
from ..core.registries import registry_network_latencies, registry_node_builders
from ..core.node import Node
from ..oracle.messages import Message
from ..oracle.network import Network, Protocol
from ..utils.more_math import log2
from .sanfermin_helper import SanFerminHelper, to_binary_id


@dataclasses.dataclass
class SanFerminSignatureParameters(WParameters):
    node_count: int = 32768 // 32
    threshold: int = 32768 // 32
    pairing_time: int = 2
    signature_size: int = 48
    reply_timeout: int = 300
    candidate_count: int = 1
    shuffled_lists: bool = False
    node_builder_name: Optional[str] = None
    network_latency_name: Optional[str] = None
    verbose: bool = False

    @property
    def power_of_two(self) -> int:
        return log2(self.node_count)


class Status(enum.Enum):
    OK = 0
    NO = 1


class SwapReply(Message):
    def __init__(self, p: "SanFerminSignature", status: Status, level: int, agg_value: int):
        self._p = p
        self.status = status
        self.level = level
        self.agg_value = agg_value

    def action(self, network, from_node, to_node):
        to_node.on_swap_reply(from_node, self)

    def size(self) -> int:
        return 4 + self._p.params.signature_size  # uint32 + sig


class SwapRequest(Message):
    def __init__(self, p: "SanFerminSignature", level: int, agg_value: int):
        self._p = p
        self.level = level
        self.agg_value = agg_value

    def action(self, network, from_node, to_node):
        to_node.on_swap_request(from_node, self)

    def size(self) -> int:
        return 4 + self._p.params.signature_size


class SanFerminNode(Node):
    __slots__ = (
        "binary_id",
        "current_prefix_length",
        "candidate_tree",
        "used_candidates",
        "signature_cache",
        "pending_nodes",
        "futur_sigs",
        "is_swapping",
        "agg_value",
        "threshold_at",
        "threshold_done",
        "done",
        "sent_requests",
        "received_requests",
        "_p",
    )

    def __init__(self, p: "SanFerminSignature", nb):
        super().__init__(p.network().rd, nb)
        self._p = p
        self.binary_id = to_binary_id(self, p.params.node_count)
        self.used_candidates: Dict[int, Set[int]] = {}
        self.candidate_tree: Optional[SanFerminHelper] = None
        self.done = False
        self.threshold_done = False
        self.threshold_at = 0
        self.sent_requests = 0
        self.received_requests = 0
        self.agg_value = 1
        # start at n with N = 2^n; decreased by go_next_level
        self.current_prefix_length = p.params.power_of_two
        self.signature_cache: Dict[int, int] = {}
        self.futur_sigs: Dict[int, int] = {}
        self.pending_nodes: Optional[Set[int]] = None  # created in go_next_level
        self.is_swapping = False

    def on_swap_request(self, node: "SanFerminNode", request: SwapRequest) -> None:
        """Fast path: the value is embedded in the request
        (SanFerminSignature.java:229-270)."""
        self.received_requests += 1
        if self.done or request.level != self.current_prefix_length:
            if request.level in self.signature_cache:
                self._print(
                    f"sending back CACHED signature at level {request.level} "
                    f"to node {node.binary_id}"
                )
                # OPTIMISTIC REPLY
                self._send_swap_reply(
                    node, Status.OK, self.signature_cache[request.level], level=request.level
                )
            else:
                self._send_swap_reply(node, Status.NO, 0)
                # a value we might want to keep for later — stored in
                # signature_cache, NOT futur_sigs, mirroring the reference
                # (SanFerminSignature.java:242-249; its futurSigs map has no
                # writer either, so the "FUTURe value" fast path is dead
                # code there too)
                is_candidate = node in self.candidate_tree.get_candidate_set(request.level)
                is_valid_sig = True  # as always :)
                if is_candidate and is_valid_sig:
                    self.signature_cache[request.level] = request.agg_value
            return

        # just send the value but don't aggregate it — OPTIMISTIC reply
        if self.is_swapping:
            self._send_swap_reply(node, Status.OK, self.agg_value, level=request.level)
            return

        is_candidate = node in self.candidate_tree.get_candidate_set(self.current_prefix_length)
        good_level = request.level == self.current_prefix_length
        is_valid_sig = True
        if is_candidate and good_level and is_valid_sig:
            self._transition("valid swap REQUEST", node.binary_id, request.level, request.agg_value)
        else:
            self._print(
                f" received  INVALID Swapfrom {node.binary_id} at level {request.level}"
            )

    def on_swap_reply(self, from_node: "SanFerminNode", reply: SwapReply) -> None:
        """(SanFerminSignature.java:272-323)."""
        p = self._p.params
        if reply.level != self.current_prefix_length or self.done:
            return
        if self.is_swapping:
            return

        if reply.status is Status.OK:
            if from_node.node_id not in self.pending_nodes:
                is_candidate = from_node in self.candidate_tree.get_candidate_set(
                    self.current_prefix_length
                )
                good_level = reply.level == self.current_prefix_length
                is_valid_sig = True
                if is_candidate and good_level and is_valid_sig:
                    self._transition(
                        "UNEXPECTED swap REPLY", from_node.binary_id, reply.level, reply.agg_value
                    )
                else:
                    self._print(
                        f" received UNEXPECTED - WRONG swap reply from {from_node.binary_id} "
                        f"at level {reply.level}"
                    )
                return
            # good valid honest answer!
            self._transition("valid swap REPLY", from_node.binary_id, reply.level, reply.agg_value)
        elif reply.status is Status.NO:
            self._print(f" received SwapReply NO from {from_node.binary_id}")
            if from_node.node_id in self.pending_nodes:
                nodes = self.candidate_tree.pick_next_nodes(
                    self.current_prefix_length, p.candidate_count
                )
                self._send_to_nodes(nodes)
            else:
                self._print(f" UNEXPECTED NO reply from {from_node.binary_id}")
        else:
            raise RuntimeError("That should never happen")

    def _send_to_nodes(self, candidates: List["SanFerminNode"]) -> None:
        """Swap request + reply-timeout task (SanFerminSignature.java:329-369)."""
        p, net = self._p, self._p.network()
        if not candidates:
            # can happen with failing/malicious nodes: nothing better to do
            self._print(" is OUT (no more nodes to pick)")
            return

        self.pending_nodes.update(n.node_id for n in candidates)
        self.sent_requests += len(candidates)

        r = SwapRequest(p, self.current_prefix_length, self.agg_value)
        self._print(
            " send SwapRequest to " + " - ".join(n.binary_id for n in candidates)
        )
        net.send(r, self, candidates)

        curr_level = self.current_prefix_length

        def on_timeout():
            if not self.done and self.current_prefix_length == curr_level:
                self._print(f"TIMEOUT of SwapRequest at level {curr_level}")
                new_list = self.candidate_tree.pick_next_nodes(
                    self.current_prefix_length, p.params.candidate_count
                )
                self._send_to_nodes(new_list)

        net.register_task(on_timeout, net.time + p.params.reply_timeout, self)

    def go_next_level(self) -> None:
        """Decrease the common-prefix requirement by one and contact the new
        candidate set (SanFerminSignature.java:379-419)."""
        p, net = self._p, self._p.network()
        if self.done:
            return

        enough_sigs = self.agg_value >= p.params.threshold
        no_more_swap = self.current_prefix_length == 0

        if enough_sigs and not self.threshold_done:
            self._print(" --- THRESHOLD REACHED --- ")
            self.threshold_done = True
            self.threshold_at = net.time + p.params.pairing_time * 2

        if no_more_swap and not self.done:
            self._print(" --- FINISHED ---- protocol")
            self.done_at = net.time + p.params.pairing_time * 2
            p.finished_nodes.append(self)
            self.done = True
            return
        self.current_prefix_length -= 1
        self.signature_cache[self.current_prefix_length] = self.agg_value
        self.is_swapping = False
        self.pending_nodes = set()
        if self.current_prefix_length in self.futur_sigs:
            self._print(
                f" FUTURe value at new level{self.current_prefix_length} saved. "
                "Moving on directly !"
            )
            self.agg_value += self.futur_sigs[self.current_prefix_length]
            self.go_next_level()
            return
        new_list = self.candidate_tree.pick_next_nodes(
            self.current_prefix_length, p.params.candidate_count
        )
        self._send_to_nodes(new_list)

    def _send_swap_reply(self, n: "SanFerminNode", s: Status, value: int, level=None) -> None:
        if level is None:
            level = self.current_prefix_length
        r = SwapReply(self._p, s, level, value)
        self._p.network().send(r, self, [n])

    def _transition(self, type_: str, from_id: str, level: int, to_aggregate: int) -> None:
        """Lock the level and aggregate after pairingTime
        (SanFerminSignature.java:434-455)."""
        p, net = self._p, self._p.network()
        self.is_swapping = True

        def do_aggregate():
            before = self.agg_value
            self.agg_value += to_aggregate
            self._print(
                f" received {type_} lvl={level} from {from_id} "
                f"aggValue {before} -> {self.agg_value}"
            )
            self.go_next_level()

        net.register_task(do_aggregate, net.time + p.params.pairing_time, self)

    def _print(self, s: str) -> None:
        if self._p.params.verbose:
            net = self._p.network()
            print(
                f"t={net.time}, id={self.node_id}, lvl={self.current_prefix_length}, "
                f"sent={self.msg_sent} -> {s}"
            )

    def __repr__(self) -> str:
        return (
            f"SanFerminNode{{nodeId={self.binary_id}, thresholdAt={self.threshold_at}, "
            f"doneAt={self.done_at}, sigs={self.agg_value}, msgReceived={self.msg_received}, "
            f"msgSent={self.msg_sent}, sentRequests={self.sent_requests}, "
            f"receivedRequests={self.received_requests}, KBytesSent={self.bytes_sent // 1024}, "
            f"KBytesReceived={self.bytes_received // 1024}}}"
        )


@register_protocol("SanFerminSignature", SanFerminSignatureParameters)
class SanFerminSignature(Protocol):
    def __init__(self, params: SanFerminSignatureParameters):
        self.params = params
        self._network: Network[SanFerminNode] = Network()
        self.nb = registry_node_builders.get_by_name(params.node_builder_name)
        self._network.set_network_latency(
            registry_network_latencies.get_by_name(params.network_latency_name)
        )
        # nodes are built in the constructor, like the reference
        # (SanFerminSignature.java:112-130)
        self.all_nodes: List[SanFerminNode] = []
        for _ in range(params.node_count):
            n = SanFerminNode(self, self.nb)
            self.all_nodes.append(n)
            self._network.add_node(n)
        for n in self.all_nodes:
            n.candidate_tree = SanFerminHelper(n, self.all_nodes, self._network.rd)
        self.finished_nodes: List[SanFerminNode] = []

    def copy(self) -> "SanFerminSignature":
        return SanFerminSignature(self.params)

    def init(self) -> None:
        for n in self.all_nodes:
            self._network.register_task(n.go_next_level, 1, n)

    def network(self) -> Network:
        return self._network


def sigs_per_time(node_ct: int = 1024, limit: int = 6000, graph_path: Optional[str] = None):
    """Scenario main (SanFerminSignature.java:566-614)."""
    from ..tools.graph import Graph, ReportLine, Series

    ps1 = SanFerminSignature(
        SanFerminSignatureParameters(node_ct, node_ct, 2, 48, 300, 1, False, None, None)
    )
    graph = Graph("number of sig per time", "time in ms", "sig count")
    s_min, s_max, s_avg = (
        Series("sig count - worse node"),
        Series("sig count - best node"),
        Series("sig count - avg"),
    )
    for s in (s_min, s_max, s_avg):
        graph.add_serie(s)
    ps1.init()
    while ps1.network().time < limit:
        ps1.network().run_ms(10)
        st = SH.get_stats_on(ps1.all_nodes, lambda n: n.agg_value)
        s_min.add_line(ReportLine(ps1.network().time, st.min))
        s_max.add_line(ReportLine(ps1.network().time, st.max))
        s_avg.add_line(ReportLine(ps1.network().time, st.avg))
    if graph_path:
        graph.save(graph_path)
    print("bytes sent:", SH.get_stats_on(ps1.all_nodes, lambda n: n.bytes_sent))
    print("bytes rcvd:", SH.get_stats_on(ps1.all_nodes, lambda n: n.bytes_received))
    print("msg sent:", SH.get_stats_on(ps1.all_nodes, lambda n: n.msg_sent))
    print("msg rcvd:", SH.get_stats_on(ps1.all_nodes, lambda n: n.msg_received))
    print(
        "done at:",
        SH.get_stats_on(
            ps1.network().all_nodes, lambda n: limit if n.done_at == 0 else n.done_at
        ),
    )
    return ps1


if __name__ == "__main__":
    sigs_per_time()

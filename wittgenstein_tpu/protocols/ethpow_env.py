"""BatchedMinerEnv: the RL bridge (ETHMinerAgent.java:38-225), TPU-first.

The reference embeds a JVM (pyjnius) and pauses a discrete-event
simulation at every agent decision point (`goNextStep`), yielding ONE
environment per process.  The TPU re-expression is a synchronous
VECTORIZED environment — the shape RL actually wants from an
accelerator: R independent replicas advance in lockstep, one policy
step covers `decision_ms` of simulated time for all of them in a single
jitted device program, and the decision events the oracle pauses on
(ON_MINED_BLOCK / ON_OTHER_NEW_HEAD / ON_OTHER_PRIVATE_HEAD,
ETHMinerAgent.java:30-36) become boolean observation columns that
report what happened since the previous step.

Per-step semantics:

  1. `actions[R]` — how many of the OLDEST withheld private blocks each
     replica's agent releases (send_mined_blocks,
     ETHMinerAgent.java:68-88); 0 = keep withholding.
  2. the simulation advances `decision_ms` (default one 10 ms mining
     beat): Bernoulli mining trials, fork choice, arrivals, and the
     agent's auto-release of overtaken blocks
     (ETHMinerAgent.java:196-203) run inside the jitted transition.
  3. observations mirror the oracle bridge's query surface:
     `advance` (getAdvance :150-157), `secret_advance`
     (getSecretAdvance :145-148), `lag` (getLag :159-166),
     `i_am_ahead` (:180-181), withheld count, head height, and the
     three decision flags; `reward_ratio` is the agent's share of the
     public winning chain (getRewardRatio :173-178 without uncle
     rewards, same scope as selfish_revenue_ratio).

Timing difference vs the oracle, by design: the oracle pauses exactly
AT each event; the vector env acts on a fixed `decision_ms` grid, so a
policy reacts up to one step later.  With the default grid equal to the
10 ms mining beat the skew is one beat against ~13 s block intervals.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from .ethpow import ETHPoWParameters
from .ethpow_batched import (
    SELFISH_ID,
    BatchedEthPow,
    EthPowState,
    replicate_ethpow,
)


class BatchedMinerEnv:
    """R lockstep selfish-mining environments in one device program."""

    def __init__(
        self,
        params: Optional[ETHPoWParameters] = None,
        n_replicas: int = 8,
        decision_ms: int = 10,
        b_max: int = 512,
        seed: int = 0,
        seeds=None,
    ):
        if params is None:
            params = ETHPoWParameters(byz_class_name="ETHMinerAgent")
        if not (params.byz_class_name or "").endswith("ETHMinerAgent"):
            raise ValueError("BatchedMinerEnv requires byz_class_name=ETHMinerAgent")
        from .ethpow_batched import BEAT_MS

        if decision_ms <= 0 or decision_ms % BEAT_MS != 0:
            # the transition advances in BEAT_MS beats until time >= end: a
            # non-multiple would overshoot every step and silently drift
            # the decision grid off the documented per-step coverage
            raise ValueError(
                f"decision_ms={decision_ms} must be a positive multiple of "
                f"the {BEAT_MS} ms mining beat"
            )
        self.net = BatchedEthPow(params, b_max=b_max, seed=seed)
        self.n_replicas = n_replicas
        self.decision_ms = decision_ms
        self._seeds = seeds
        self._states: Optional[EthPowState] = None

        net = self.net

        def transition(s: EthPowState, action) -> EthPowState:
            s = net.agent_apply_action(s, action)
            end = s.time + decision_ms
            return lax.while_loop(lambda x: x.time < end, net._beat, s)

        self._transition = jax.jit(jax.vmap(transition))
        self._observe = jax.jit(jax.vmap(self._obs_one))

    # -- observation pieces (single replica; vmapped) ------------------------
    def _obs_one(self, s: EthPowState, prev: EthPowState):
        sm = SELFISH_ID
        hgt, prod, par, td = s.height, s.producer, s.parent, s.td
        head = s.head[sm]

        # advance: consecutive own blocks from the head down (getAdvance)
        def walk(cond_fn):
            def body(c):
                i, n = c
                return par[i], n + 1

            return lax.while_loop(
                lambda c: cond_fn(c[0]) & (c[0] != 0), body, (head, jnp.int32(0))
            )[1]

        advance = walk(lambda i: prod[i] == sm)
        lag = walk(lambda i: prod[i] != sm)
        ph = jnp.where(s.pmb >= 0, hgt[s.pmb], 0)
        secret_advance = jnp.maximum(ph - hgt[s.omh], 0)

        # reward ratio over the PUBLIC winning chain, observed by the
        # honest miner 0 (chain_producers' scope)
        known = s.arrival[:, 0] <= s.time
        tip = jnp.argmax(jnp.where(known, td, -1.0)).astype(jnp.int32)

        def rbody(c):
            i, mine, tot = c
            return par[i], mine + (prod[i] == sm), tot + 1

        _, mine, total = lax.while_loop(
            lambda c: c[0] != 0, rbody, (tip, jnp.int32(0), jnp.int32(0))
        )
        ratio = mine / jnp.maximum(total, 1)

        return {
            "time": s.time,
            "head_height": hgt[head],
            "advance": advance,
            "secret_advance": secret_advance,
            "lag": lag,
            "i_am_ahead": prod[head] == sm,
            "n_withheld": jnp.sum(s.withheld.astype(jnp.int32)),
            "reward_ratio": ratio,
            # decision flags: what the oracle would have paused on since
            # the previous step
            "mined_block": s.blocks_mined[sm] > prev.blocks_mined[sm],
            "other_new_head": (s.head[sm] != prev.head[sm])
            & (prod[s.head[sm]] != sm),
            "other_private_head": s.omh != prev.omh,
        }

    # -- gym-style surface ---------------------------------------------------
    def reset(self):
        state = self.net.init_state()
        self._states = replicate_ethpow(state, self.n_replicas, self._seeds)
        obs = self._observe(self._states, self._states)
        return {k: np.asarray(v) for k, v in obs.items()}

    def step(self, actions):
        """actions: int array [R] — oldest withheld blocks to release."""
        if self._states is None:
            raise RuntimeError("call reset() first")
        prev = self._states
        acts = jnp.asarray(actions, jnp.int32).reshape(self.n_replicas)
        self._states = self._transition(prev, acts)
        obs = self._observe(self._states, prev)
        obs = {k: np.asarray(v) for k, v in obs.items()}
        reward = obs["reward_ratio"]
        return obs, reward, {"overflowed": np.asarray(self._states.overflowed)}

    @property
    def states(self) -> EthPowState:
        return self._states

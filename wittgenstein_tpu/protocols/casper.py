"""Casper IMD — beacon chain stage 1 (no justification, no dynasty changes),
per the ethresear.ch mini-spec: one block producer per 8-second slot,
attester committees voting per slot, GHOST-like fork choice counting
attestations down to the first common ancestor.

Reference semantics: protocols/CasperIMD.java (Attestation :105-149,
CasperBlock :151-194, fork choice `best`/countAttestations :204-288,
slot-clock gate in onBlock :298-314, buildBlock merge :383-428, init task
schedule :472-508, Byzantine producers :511-707).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Set

from ..core.params import WParameters, register_protocol
from ..core.registries import registry_network_latencies, registry_node_builders
from ..oracle.blockchain import Block, BlockChainNetwork, BlockChainNode, SendBlock
from ..oracle.messages import Message
from ..oracle.network import Protocol

SLOT_DURATION = 8000


@dataclasses.dataclass
class CasperParameters(WParameters):
    cycle_length: int = 4  # rounds per cycle; 64 in the spec
    random_on_ties: bool = True
    block_producers_count: int = 2
    attesters_per_round: int = 20
    block_construction_time: int = 1000
    attestation_construction_time: int = 1
    node_builder_name: Optional[str] = None
    network_latency_name: Optional[str] = None

    @property
    def attesters_count(self) -> int:
        return self.attesters_per_round * self.cycle_length


class Attestation(Message):
    """A vote for a head is a vote for all its ancestors within cycleLength
    (CasperIMD.java:105-149); `hs` holds the ancestor ids of head's PARENT."""

    def __init__(self, attester: "Attester", height: int):
        self.attester = attester
        self.height = height
        self.head = attester.head
        self.hs: Set[int] = set()
        cycle_length = attester._p.params.cycle_length
        cur = attester.head.parent
        while cur is not None and cur.height >= attester.head.height - cycle_length:
            self.hs.add(cur.id)
            cur = cur.parent

    def action(self, network, from_node, to_node):
        to_node.on_attestation(self)

    def attests(self, cb: Block) -> bool:
        return cb.id in self.hs

    def __repr__(self):
        return (
            f"Attestation{{attester={self.attester.node_id}, height={self.height}, "
            f"ids={len(self.hs)}}}"
        )


class CasperBlock(Block):
    def __init__(
        self,
        block_producer: Optional["BlockProducer"] = None,
        height: int = 0,
        father: Optional["CasperBlock"] = None,
        attestations_by_height: Optional[Dict[int, Set[Attestation]]] = None,
        time: int = 0,
        genesis: bool = False,
    ):
        if genesis:
            super().__init__(height=0, genesis=True)
            self.attestations_by_height: Dict[int, Set[Attestation]] = {}
            return
        super().__init__(block_producer, height, father, True, time)
        self.attestations_by_height = attestations_by_height or {}

    def __repr__(self):
        if self.id == 0:
            return "genesis"
        return (
            f"{{ height={self.height}, id={self.id}, proposalTime={self.proposal_time}, "
            f"parent={self.parent.id}}}"
        )


class CasperNode(BlockChainNode):
    __slots__ = ("attestations_by_head", "blocks_to_reevaluate", "_p")

    def __init__(self, p: "CasperIMD", byzantine: bool, genesis: CasperBlock):
        super().__init__(p.network().rd, p.nb, byzantine, genesis)
        self._p = p
        self.attestations_by_head: Dict[int, Set[Attestation]] = {}
        self.blocks_to_reevaluate: Set[CasperBlock] = set()

    def best(self, o1: CasperBlock, o2: CasperBlock) -> CasperBlock:
        """GHOST-ish fork choice (CasperIMD.java:204-257)."""
        net, params = self._p.network(), self._p.params
        if o1 is o2:
            return o1
        if o1.height == o2.height:
            # two blocks for one height: slashable, unsupported
            raise RuntimeError(f"same height: {o1}, {o2}")
        if o1.has_direct_link(o2):
            return o2 if o1.height < o2.height else o1

        # phase 1: find the first common ancestor 'H'
        b1, b2 = o1, o2
        while b1.parent is not b2.parent:
            assert b1.parent.height != b2.parent.height
            if b1.parent.height > b2.parent.height:
                b1 = b1.parent
            else:
                b2 = b2.parent
        h = b1.parent

        # phase 2: count the votes on each branch
        b1_votes = self.count_attestations(o1, h)
        b2_votes = self.count_attestations(o2, h)
        if b1_votes > b2_votes:
            return o1
        if b1_votes < b2_votes:
            return o2
        if params.random_on_ties:
            return o1 if net.rd.next_boolean() else o2
        return o1 if b1.id >= b2.id else o2

    def count_attestations(self, start: CasperBlock, h: CasperBlock) -> int:
        """Attestations for 'h' on the branch ending at 'start', counting
        in-block and directly-received ones once (CasperIMD.java:262-288)."""
        a1: Set[Attestation] = set()
        cur = start
        while cur is not h:
            assert cur is not None
            for i in range(cur.height - 1, h.height, -1):
                for a in cur.attestations_by_height.get(i, ()):
                    if a.attests(h):
                        a1.add(a)
            for a in self.attestations_by_head.get(cur.id, ()):
                if a.attests(h):
                    a1.add(a)
            cur = cur.parent
        return len(a1)

    def on_block(self, b: CasperBlock) -> bool:
        """Slot-clock gate (CasperIMD.java:298-314)."""
        net, params = self._p.network(), self._p.params
        delta = net.time - self.genesis.proposal_time + b.height * SLOT_DURATION
        if delta >= 0:
            self.blocks_to_reevaluate.add(self.head)  # head may win later
            self.blocks_to_reevaluate.add(b)
            return super().on_block(b)
        net.register_task(lambda: self.on_block(b), -delta, self)
        return False

    def on_attestation(self, a: Attestation) -> None:
        """(CasperIMD.java:316-337) — attestations are keyed by the head
        they were made on, never reused across branches."""
        self.attestations_by_head.setdefault(a.head.id, set()).add(a)
        if a.head.id in self.blocks_received_by_block_id:
            self.blocks_to_reevaluate.add(a.head)

    def reevaluate_head(self) -> None:
        """Lazy head re-election before emitting (CasperIMD.java:348-353)."""
        for b in self.blocks_to_reevaluate:
            self.head = self.best(self.head, b)
        self.blocks_to_reevaluate.clear()

    def periodic_task(self):
        return None

    def __repr__(self):
        return f"CasperNode{{nodeId={self.node_id}}}"


class BlockProducer(CasperNode):
    __slots__ = ()

    def __init__(self, p: "CasperIMD", genesis: CasperBlock, byzantine: bool = False):
        super().__init__(p, byzantine, genesis)

    def periodic_task(self):
        def task():
            self.reevaluate_head()
            self.create_and_send_block(self._p.network().time // SLOT_DURATION)

        return task

    def build_block(self, base: CasperBlock, height: int) -> CasperBlock:
        """Include every known attestation not yet on the chain
        (CasperIMD.java:383-428)."""
        params, net = self._p.params, self._p.network()
        res: Dict[int, Set[Attestation]] = {}
        i = height - 1
        while i >= 0 and i >= height - params.cycle_length:
            res[i] = set()
            i -= 1

        # phase 1: attestations already included in parent blocks
        all_from_blocks: Set[Attestation] = set()
        cur = base
        while cur is not self.genesis and cur.height >= height - params.cycle_length:
            for ats in cur.attestations_by_height.values():
                all_from_blocks.update(ats)
            cur = cur.parent

        # phase 2: add the missing ones we received directly
        cur = base
        while cur is not None and cur.height >= height - params.cycle_length:
            for a in self.attestations_by_head.get(cur.id, ()):
                if a.height < height and a not in all_from_blocks:
                    res.setdefault(a.height, set()).add(a)
            cur = cur.parent

        return CasperBlock(self, height, base, res, net.time)

    def create_and_send_block(self, height: int) -> None:
        net, params = self._p.network(), self._p.params
        self.head = self.build_block(self.head, height)
        net.send_all(SendBlock(self.head), self, net.time + params.block_construction_time)

    def __repr__(self):
        return f"BlockProducer{{nodeId={self.node_id}}}"


class Attester(CasperNode):
    __slots__ = ()

    def __init__(self, p: "CasperIMD", genesis: CasperBlock):
        super().__init__(p, False, genesis)

    def periodic_task(self):
        def task():
            self.vote(self._p.network().time // SLOT_DURATION)

        return task

    def vote(self, height: int) -> None:
        """Re-elect the head 4 s into the slot, then attest
        (CasperIMD.java:455-464)."""
        net, params = self._p.network(), self._p.params
        self.reevaluate_head()
        v = Attestation(self, height)
        net.send_all(v, self, net.time + params.attestation_construction_time)

    def __repr__(self):
        return f"Attester{{nodeId={self.node_id}}}"


class ByzBlockProducer(BlockProducer):
    """Waits `delay` ms before sending its block (CasperIMD.java:511-580)."""

    __slots__ = ("to_send", "h", "delay", "on_direct_father", "on_older_ancestor",
                 "inc_not_the_best_father")

    def __init__(self, p: "CasperIMD", delay: int, genesis: CasperBlock):
        super().__init__(p, genesis, byzantine=True)
        self.to_send = 1
        self.h = 0
        self.delay = delay
        self.on_direct_father = 0
        self.on_older_ancestor = 0
        self.inc_not_the_best_father = 0

    def reevaluate_h(self, time: int) -> None:
        """Recompute head & slot accounting for our delay
        (CasperIMD.java:529-542)."""
        self.reevaluate_head()
        while self.head.height >= self.to_send:
            self.head = self.head.parent
        slot_time = time - self.delay
        self.h = slot_time // SLOT_DURATION
        if self.h != self.to_send:
            raise RuntimeError(f"h={self.h}, toSend={self.to_send}")

    def periodic_task(self):
        def task():
            self.reevaluate_h(self._p.network().time)
            if self.head.height == self.h - 1:
                self.on_direct_father += 1
            else:
                self.on_older_ancestor += 1
                # deterministic pick (the reference takes an arbitrary
                # HashSet element here)
                rcv = self.blocks_received_by_height.get(self.h - 1, set())
                possible_father = min(rcv, key=lambda b: b.id) if rcv else None
                if possible_father is not None and possible_father.parent.height != self.h - 1:
                    self.inc_not_the_best_father += 1
            self.create_and_send_block(self.to_send)
            self.to_send += self._p.params.block_producers_count

        return task

    def __repr__(self):
        return (
            f"{type(self).__name__}{{delay={self.delay}, "
            f"onDirectFather={self.on_direct_father}, "
            f"onOlderAncestor={self.on_older_ancestor}, "
            f"incNotTheBestFather={self.inc_not_the_best_father}}}"
        )


class ByzBlockProducerSF(ByzBlockProducer):
    """Skips its father's block to steal its transactions
    (CasperIMD.java:583-604)."""

    __slots__ = ()

    def periodic_task(self):
        def task():
            self.reevaluate_h(self._p.network().time)
            if self.head.id != 0 and self.head.height == self.h - 1:
                self.head = self.head.parent
                self.on_direct_father += 1
            else:
                self.on_older_ancestor += 1
            self.create_and_send_block(self.to_send)
            self.to_send += self._p.params.block_producers_count

        return task


class ByzBlockProducerNS(ByzBlockProducer):
    """Skips its father if the father skipped the grandfather
    (CasperIMD.java:610-640)."""

    __slots__ = ("skipped",)

    def __init__(self, p: "CasperIMD", delay: int, genesis: CasperBlock):
        super().__init__(p, delay, genesis)
        self.skipped = 0

    def periodic_task(self):
        def task():
            self.reevaluate_h(self._p.network().time)
            if (
                self.head.id != 0
                and self.head.height == self.h - 1
                and self.head.parent.height == self.h - 3
            ):
                rcv = self.blocks_received_by_height.get(self.h - 2, set())
                b = min(rcv, key=lambda blk: blk.id) if rcv else None
                if b is not None:
                    self.head = b
                    self.skipped += 1
            self.create_and_send_block(self.to_send)
            self.to_send += self._p.params.block_producers_count

        return task

    def __repr__(self):
        return f"ByzantineBPNS{{delay={self.delay}, skipped={self.skipped}}}"


class ByzBlockProducerWF(ByzBlockProducer):
    """Waits for the previous block before applying its delay
    (CasperIMD.java:647-707)."""

    __slots__ = ("late", "on_time")

    def __init__(self, p: "CasperIMD", delay: int, genesis: CasperBlock):
        super().__init__(p, delay, genesis)
        self.late = 0
        self.on_time = 0

    def periodic_task(self):
        def task():
            if self.head is self.genesis and self.to_send == 1:
                # first producer kicks off the system
                self.reevaluate_h(self._p.network().time)
                self.create_and_send_block(self.h)
                self.to_send += self._p.params.block_producers_count

        return task

    def on_block(self, b: CasperBlock) -> bool:
        net, params = self._p.network(), self._p.params
        if super().on_block(b):
            if b.height == self.to_send - 1:
                perfect_date = SLOT_DURATION * self.to_send + self.delay
                th = self.to_send

                def r():
                    self.head = self.build_block(b, th)
                    net.send_all(
                        SendBlock(self.head), self, net.time + params.block_construction_time
                    )

                self.to_send += params.block_producers_count
                if net.time >= perfect_date:
                    r()
                    self.late += 1
                else:
                    net.register_task(r, perfect_date, self)
                    self.on_time += 1
            return True
        return False

    def __repr__(self):
        return f"ByzantineBPWF{{delay={self.delay}, late={self.late}, onTime={self.on_time}}}"


class _ObserverNode(CasperNode):
    __slots__ = ()


@register_protocol("CasperIMD", CasperParameters)
class CasperIMD(Protocol):
    def __init__(self, params: CasperParameters):
        self.params = params
        self._network: BlockChainNetwork = BlockChainNetwork()
        self.nb = registry_node_builders.get_by_name(params.node_builder_name)
        self._network.set_network_latency(
            registry_network_latencies.get_by_name(params.network_latency_name)
        )
        self.genesis = CasperBlock(genesis=True)
        self.attesters: List[Attester] = []
        self.bps: List[BlockProducer] = []
        self._network.add_observer(_ObserverNode(self, False, self.genesis))

    def network(self) -> BlockChainNetwork:
        return self._network

    def copy(self) -> "CasperIMD":
        return CasperIMD(self.params)

    def init(self, byzantine_node: Optional[ByzBlockProducer] = None) -> None:
        """Task schedule (CasperIMD.java:472-508): producer i fires at slot
        i+1, attester committee c fires 4 s into slot 1+c."""
        p, net = self.params, self._network
        if byzantine_node is None:
            byzantine_node = ByzBlockProducerWF(self, 0, self.genesis)
        self.bps.append(byzantine_node)
        net.add_node(byzantine_node)
        net.register_periodic_task(
            byzantine_node.periodic_task(),
            SLOT_DURATION + byzantine_node.delay,
            SLOT_DURATION * p.block_producers_count,
            byzantine_node,
        )
        for i in range(1, p.block_producers_count):
            n = BlockProducer(self, self.genesis)
            self.bps.append(n)
            net.add_node(n)
            net.register_periodic_task(
                n.periodic_task(),
                SLOT_DURATION * (i + 1),
                SLOT_DURATION * p.block_producers_count,
                n,
            )
        for i in range(p.attesters_count):
            n = Attester(self, self.genesis)
            self.attesters.append(n)
            net.add_node(n)
            net.register_periodic_task(
                n.periodic_task(),
                SLOT_DURATION * (1 + i % p.cycle_length) + 4000,
                SLOT_DURATION * p.cycle_length,
                n,
            )

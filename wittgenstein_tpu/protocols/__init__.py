"""Protocol implementations.

Each protocol has an oracle implementation (exact DES semantics, classes on
wittgenstein_tpu.oracle) and — for the performance-critical families — a
batched TPU implementation (kernels on wittgenstein_tpu.core.engine).
Importing this package registers every protocol in
wittgenstein_tpu.core.params.protocol_registry (the API-discovery contract).
"""

from . import (  # noqa: F401
    casper,
    dfinity,
    enr_gossiping,
    ethpow,
    gsf,
    handel,
    handeleth2,
    optimistic_p2p_signature,
    p2pflood,
    p2phandel,
    paxos,
    pingpong,
    sanfermin,
    sanfermin_cappos,
    slush,
    snowflake,
)

__all__ = [
    "casper",
    "dfinity",
    "enr_gossiping",
    "ethpow",
    "gsf",
    "handel",
    "handeleth2",
    "optimistic_p2p_signature",
    "p2pflood",
    "p2phandel",
    "paxos",
    "pingpong",
    "sanfermin",
    "sanfermin_cappos",
    "slush",
    "snowflake",
]

"""The batching scheduler: compatible jobs share one compiled program,
compatibility families share the machine through wave packing.

Grouping discipline
-------------------
Two jobs may ride the same ``run_ms_batched`` dispatch iff they resolve
to the same **scenario family**: protocol name + every traced param
(anything not in the serve registry's ``state_only`` set) + execution
geometry.  For a direct (single device call) job the geometry is the
simulation horizon; for a chunked job it is the CHUNK UNIT only — the
horizon itself is per-job data ("horizon sharding"): a job's total
sim_ms is split at admission into fixed-length units
(``jobs.chunk_schedule``), so tenants with different horizons pack into
the same replica-axis batches and finish at their own chunk boundaries
instead of fragmenting into per-horizon compiled programs.  That
pre-key is computed at admission from the spec alone; when the family
is first built, the full static digest is extended with
``runtime.supervisor.stable_run_key`` over the engine + template leaf
signature — the same digest discipline the durable executor stamps into
checkpoints — so "compatible" is defined by what actually shapes the
trace, not by what the client claimed.  Everything else a job carries —
seed, FaultPlan, state-only params — is per-replica DATA.

Fixed-compile guarantee
-----------------------
Every dispatch is padded to a fixed replica capacity
(``max_batch_replicas``; padding rows are template copies whose results
are discarded), so every batch of a family presents the identical input
leaf signature to the run cache (parallel.replica_shard): ONE compile
per (family, unit) however the workload arrives.  The run cache's
monotonic hit/miss/compile counters make the claim measurable — the
loadgen asserts it.  (A quantum remainder — sim_ms not divisible by the
unit — costs one extra 1-row program per distinct remainder length;
divisible horizons stay inside the fixed-compile envelope.)

Families hold ONE engine object each on purpose: ``net.cache_key()``
includes ``id(protocol)``/``id(latency)``, so rebuilding the engine per
job would defeat the cache even with identical params (simlint SL801
pins this contract).

Wave packing (dispatch lanes)
-----------------------------
The scheduler runs G dispatch lanes (``device_groups``), each bound to
its own slice of the visible devices (parallel.device_groups): up to G
compatibility families execute CONCURRENTLY, one per lane, instead of
serializing through one worker.  A family is STICKY to the lane that
first dispatches it — lane placement is part of the compiled program's
input sharding, so stickiness is what keeps the one-compile-per-family
contract under wave packing.  Claiming (queue pops, parked-batch
resumes, family→lane binding) is serialized under one dispatch lock;
device execution happens outside it.  With the default
``device_groups=1`` there is exactly one lane with NO explicit
placement — bit-for-bit the legacy single-worker scheduler.  Results
are bitwise identical across lane layouts either way: replica rows are
elementwise lane-independent under vmap, so placement can change only
where a row computes, never its bytes.

Preemption
----------
A chunked batch runs through ``runtime.Supervisor`` in slices of
``slice_chunks`` device calls, checkpointing every chunk via
``engine/checkpoint.CheckpointManager``.  Between slices its lane
checks the queue: claimable work with strictly higher priority parks
the batch (its checkpoint is the park ticket) and runs first; the
parked batch later resumes from the checkpoint, bit-identical to an
uninterrupted run (the supervisor's replay contract).  Slices also stop
exactly at every member job's horizon boundary, where the finished rows
are captured and finalized while the rest of the batch keeps running.
The chunk function is routed through the SAME run cache, so the chunked
mode costs one extra compile per family, not one per slice.
"""

from __future__ import annotations

import hashlib
import itertools
import json
import os
import shutil
import tempfile
import threading
import time
from typing import Callable, Dict, List, Optional

from ..obs import (
    FlightRecorder,
    InvariantSentinel,
    SLOEngine,
    TraceContext,
    batch_attribution,
    default_serve_specs,
    get_recorder,
    load_capacity_table,
    mint_context,
)
from ..runtime.errors import (
    RETRYABLE_KINDS,
    LaneFailedError,
    PoisonRowError,
    classify,
)
from ..runtime.locks import make_lock, yield_point
from ..runtime.policy import SalvagePolicy
from .jobs import (
    DrainingError,
    Job,
    JobQueue,
    JobSpec,
    JobState,
    QueueFullError,
    chunk_schedule,
    serve_protocol,
)
from .metrics import ServeMetrics


def _job_ctx(job: Job) -> TraceContext:
    """The admission-minted identity of a job as a TraceContext."""
    return TraceContext(
        run_id=job.run_id,
        job_id=job.id,
        tenant_id=job.spec.tenant if job.spec is not None else None,
    )


def _leaf_signature(state) -> tuple:
    """(path, shape, dtype) per leaf — rows packed together must agree
    exactly or the stacked program would differ from the family's."""
    import jax

    sig = []
    for path, leaf in jax.tree_util.tree_flatten_with_path(state)[0]:
        sig.append(
            (
                str(path),
                tuple(getattr(leaf, "shape", ())),
                str(getattr(leaf, "dtype", type(leaf).__name__)),
            )
        )
    return tuple(sig)


def state_digest(state) -> str:
    """Bitwise identity of a state pytree (side-cars included): blake2b
    over every leaf's path, dtype, shape, and raw bytes.  Two runs are
    'the same result' iff these match — the multi-tenant correctness
    contract (batched row == singleton run) is checked on this."""
    import jax
    import numpy as np

    h = hashlib.blake2b(digest_size=16)
    for path, leaf in jax.tree_util.tree_flatten_with_path(state)[0]:
        arr = np.asarray(leaf)
        h.update(str(path).encode())
        h.update(str(arr.dtype).encode())
        h.update(str(arr.shape).encode())
        h.update(arr.tobytes())
    return h.hexdigest()


class ScenarioFamily:
    """One compatibility class: a single engine object + per-params
    single-replica templates, all sharing one traced program.

    ``mode`` is "direct" (one device call per batch, horizon traced
    into the program) or "chunked" (``unit_ms`` steps through the
    Supervisor; horizons are per-job data)."""

    def __init__(self, key, digest, net, entry, tele_cfg, sim_ms, chunk_ms,
                 base_params_key, base_template, mode="direct",
                 unit_ms=None):
        self.key = key  # admission-time pre-key
        self.digest = digest  # full static digest (stable_run_key suffix)
        self.net = net
        self.entry = entry
        self.tele_cfg = tele_cfg
        self.sim_ms = sim_ms  # first-seen horizon (informational only)
        self.chunk_ms = chunk_ms
        self.mode = mode
        self.unit_ms = unit_ms if unit_ms is not None else (chunk_ms or sim_ms)
        self.templates: Dict[str, object] = {base_params_key: base_template}
        self.signature = _leaf_signature(base_template)


class _Lane:
    """One dispatch lane: a worker thread bound to a device group (or,
    for the single-lane scheduler, to no explicit placement at all)."""

    def __init__(self, index: int, group=None):
        self.index = index
        self.group = group  # parallel.device_groups.DeviceGroup | None
        self.thread: Optional[threading.Thread] = None
        self.busy = False
        self.dispatches = 0
        self.busy_seconds = 0.0
        # supervision state: a lane thread that dies (exception or
        # injected kill) is restarted by _on_lane_failure; fail_streak
        # paces the restart backoff and resets on the next clean claim
        self.restarts = 0
        self.fail_streak = 0
        self.kill_requested = False
        self.abandoned = False
        # trace ids of the batch this lane last dispatched: when the
        # worker thread dies, the lane-failure alert names the victim
        # run instead of an anonymous lane index
        self.last_ctx: Optional[TraceContext] = None

    def alive(self) -> bool:
        return self.thread is not None and self.thread.is_alive()

    def describe(self) -> dict:
        return {
            "index": self.index,
            "devices": (
                [str(d) for d in self.group.devices]
                if self.group is not None
                else None
            ),
            "busy": self.busy,
            "dispatches": self.dispatches,
            "busySeconds": round(self.busy_seconds, 4),
            "alive": self.alive(),
            "restarts": self.restarts,
        }


class _ParkedBatch:
    """A chunked batch between slices: the Supervisor (whose checkpoint
    directory is the resume ticket) plus the jobs riding it.  With
    horizon sharding the member jobs may have different chunk counts
    (``job_chunks``); each job's row is captured and finalized at its
    own boundary while the batch runs on to the longest horizon."""

    def __init__(self, batch_id, family, jobs, supervisor, ckpt_dir,
                 priority, capacity, lane=0, job_chunks=None,
                 job_rems=None):
        self.batch_id = batch_id
        self.family = family
        self.jobs = jobs
        self.supervisor = supervisor
        self.ckpt_dir = ckpt_dir
        self.priority = priority
        self.capacity = capacity
        self.lane = lane
        self.job_chunks = job_chunks or []
        self.job_rems = job_rems or [0] * len(jobs)
        self.chunks_done = 0
        self.finished: set = set()  # job ids finalized at a boundary
        self.preempted = False
        self.running = False  # claimed by a lane this instant
        self.started = time.monotonic()


class BatchScheduler:
    """Queue consumer: groups, packs, dispatches, streams progress.

    ``device_groups`` lanes each run one worker thread (wave packing);
    the default of 1 is the legacy single-worker scheduler (the engine
    is replica-parallel, not request-parallel, within a lane).  HTTP
    handlers only touch the queue and job records.  ``auto_start=False``
    leaves the workers off so tests can drive ``drain_once()``
    deterministically (lane 0 unless told otherwise)."""

    def __init__(
        self,
        queue: Optional[JobQueue] = None,
        metrics: Optional[ServeMetrics] = None,
        *,
        max_batch_replicas: int = 8,
        slice_chunks: int = 2,
        telemetry_snapshots: int = 32,
        checkpoint_root: Optional[str] = None,
        auto_start: bool = True,
        recorder: Optional[FlightRecorder] = None,
        device_groups: int = 1,
        node_parallel: int = 1,
        horizon_quantum_ms: int = 0,
        binding_ttl_s: float = 300.0,
        salvage: Optional[SalvagePolicy] = None,
        lane_restart_limit: int = 0,
        harvest: bool = True,
    ):
        if max_batch_replicas < 1:
            raise ValueError(
                f"max_batch_replicas must be >= 1, got {max_batch_replicas}"
            )
        if horizon_quantum_ms < 0:
            raise ValueError(
                f"horizon_quantum_ms must be >= 0, got {horizon_quantum_ms}"
            )
        self.queue = queue or JobQueue()
        self.metrics = metrics or ServeMetrics()
        self.max_batch_replicas = max_batch_replicas
        self.slice_chunks = max(1, slice_chunks)
        self.telemetry_snapshots = telemetry_snapshots
        self.horizon_quantum_ms = horizon_quantum_ms
        self.checkpoint_root = checkpoint_root or os.path.join(
            tempfile.gettempdir(), f"witt_serve_ckpt_{os.getpid()}"
        )
        self.auto_start = auto_start
        # flight recorder: admission / packing / dispatch decisions land
        # here alongside the supervisor's chunk events (one ring per
        # process by default, see obs.get_recorder)
        self.recorder = recorder if recorder is not None else get_recorder()
        self._families: Dict[str, ScenarioFamily] = {}
        self._fam_lock = make_lock("serve.family")
        self._parked: List[_ParkedBatch] = []
        self._batch_seq = 0
        # Retry-After pacing: per-family EMA of batch wall time (a slow
        # Handel family must not inflate a fast p2pflood tenant's
        # backoff hint) with the global EMA as the cold-family fallback
        self._ema_batch_s = 1.0
        self._ema_family: Dict[str, float] = {}
        # wave packing: lane list + the dispatch lock that serializes
        # every claim decision (queue pops, parked resumes, family→lane
        # binding); device execution runs outside it
        if device_groups < 1:
            raise ValueError(
                f"device_groups must be >= 1, got {device_groups}"
            )
        if node_parallel < 1:
            raise ValueError(
                f"node_parallel must be >= 1, got {node_parallel}"
            )
        if device_groups == 1 and node_parallel == 1:
            # no explicit placement: bit-for-bit the legacy scheduler
            # (and no re-placement cost for the common case)
            self._lanes = [_Lane(0, None)]
        else:
            from ..parallel.device_groups import make_device_groups

            self._lanes = [
                _Lane(g.index, g)
                for g in make_device_groups(
                    device_groups, node_parallel=node_parallel
                )
            ]
        self.device_groups = len(self._lanes)
        self.node_parallel = node_parallel
        self._dispatch_lock = make_lock("serve.dispatch")
        self._family_lane: Dict[str, int] = {}
        self._active_dispatches = 0
        self._worker_lock = make_lock("serve.worker")
        self._stop = threading.Event()
        # -- fleet resilience ------------------------------------------
        # sticky bindings expire once a family has had no queued work
        # and no parked batch for this long (<= 0: expire immediately
        # when idle): the family's compiled programs stay in the run
        # cache either way — expiry only re-decides the LANE, so a dead
        # family stops pinning lane choice forever (the PR-13 leak).  A
        # re-bind to a different lane costs one re-place compile only
        # when device_groups > 1.
        self.binding_ttl_s = binding_ttl_s
        self._binding_used: Dict[str, float] = {}
        # batch salvage: a failed packed batch is bisected to isolate
        # the poison row instead of failing every rider (runtime.policy)
        self.salvage = salvage if salvage is not None else SalvagePolicy()
        # 0 = restart crashed lanes forever; > 0 = abandon after N
        self.lane_restart_limit = lane_restart_limit
        # done-row harvesting (ISSUE 18): after members finalize at
        # their horizon boundaries, compact the survivors into the
        # next-smaller power-of-two capacity bucket and re-park, so a
        # mostly-finished batch stops re-running its dead width every
        # slice.  Bitwise-neutral per row (vmap rows are independent;
        # the salvage-bisection precedent); default-ON per the paired
        # A/B in BENCH_SERVE.json — +40% aggregate sims/s on the
        # mixed-horizon scenario, within noise on uniform horizons
        # where it never fires (profiling.md lever ledger)
        self.harvest = bool(harvest)
        # graceful drain: admission refuses, lanes stop claiming,
        # in-flight chunked slices checkpoint-stop (Supervisor
        # should_stop); pending + parked work survives for undrain
        self._draining = threading.Event()
        # chaos hook (tests, scripts/chaos_smoke.py): called with
        # (family, jobs) immediately before EVERY device run — batch
        # dispatches and salvage probes alike — so an injected poison
        # fails exactly the subsets containing it and bisection can
        # isolate it.  run_singleton never calls it: reference results
        # stay fault-free.
        self.chaos_injector: Optional[Callable] = None
        # mission control: burn-rate SLOs over the metrics history
        # (obs/slo.py).  Evaluation is pull-driven — health(),
        # slo_status(), and /metrics each evaluate, so any poller keeps
        # the alert state fresh; alerts edge-trigger typed
        # flight-recorder events under the victim sample's run_id.
        self.slo = SLOEngine(
            self.metrics.timeseries,
            default_serve_specs(),
            recorder=self.recorder,
        )
        # runtime invariant sentinel input: the CAPACITY.json sizing
        # promises, loaded once (a fresh sentinel per chunked batch
        # keeps the per-invariant alert latch per-run)
        self._capacity_table = load_capacity_table()

    # -- admission -----------------------------------------------------

    def _schedule_for(self, spec: JobSpec) -> List[int]:
        return chunk_schedule(
            spec.sim_ms, spec.chunk_ms, self.horizon_quantum_ms
        )

    def _is_chunked(self, spec: JobSpec) -> bool:
        """Chunked execution: explicit chunkMs, or the scheduler
        quantum covers this horizon (sim_ms == quantum is one unit of
        the SHARED chunked family, not a private direct program)."""
        return bool(spec.chunk_ms) or (
            self.horizon_quantum_ms > 0
            and spec.sim_ms >= self.horizon_quantum_ms
        )

    def pre_key(self, spec: JobSpec) -> str:
        """Compatibility pre-key from the spec alone (no engine build):
        protocol + traced params + execution geometry + telemetry
        config.  Chunked jobs key on the chunk UNIT, not the horizon —
        horizon sharding packs mixed-simMs tenants into one family.
        Jobs sharing the pre-key are CANDIDATES for one batch; the
        family build extends it with the template leaf signature."""
        entry = serve_protocol(spec.protocol)
        traced = {
            k: spec.params[k]
            for k in sorted(spec.params)
            if k not in entry.state_only
        }
        schedule = self._schedule_for(spec)
        chunked = self._is_chunked(spec)
        horizon = (
            {"mode": "chunked", "unit_ms": schedule[0]}
            if chunked
            else {"mode": "direct", "sim_ms": spec.sim_ms}
        )
        payload = json.dumps(
            {
                "protocol": spec.protocol,
                "traced": traced,
                "snapshots": self.telemetry_snapshots,
                **horizon,
            },
            sort_keys=True,
            default=str,
        )
        return "fam-" + hashlib.blake2b(
            payload.encode(), digest_size=8
        ).hexdigest()

    def retry_after_s(self, compat: Optional[str] = None) -> int:
        """Seconds until queued work likely drains one batch slot (RFC
        9110: >= 1).  With a known family the estimate is paced from
        THAT family's batch-time EMA over THAT family's backlog; the
        global EMA over the whole queue is the cold/unknown fallback."""
        if compat is not None and compat in self._ema_family:
            ema = self._ema_family[compat]
            depth = self.queue.depth_for(compat)
        else:
            ema = self._ema_batch_s
            depth = self.queue.depth()
        batches_ahead = depth // self.max_batch_replicas + 1
        # families drain concurrently across lanes: the wait shortens by
        # the wave width the fleet can actually sustain
        lanes = max(1, self.device_groups)
        return max(1, int(batches_ahead * ema / lanes + 0.5))

    def _note_batch_time(self, compat: Optional[str], dt: float) -> None:
        # lanes finish batches concurrently: the EMA read-modify-write
        # must not interleave (SL1305)
        with self._dispatch_lock:
            self._ema_batch_s = 0.5 * self._ema_batch_s + 0.5 * dt
            if compat:
                prev = self._ema_family.get(compat)
                self._ema_family[compat] = (
                    dt if prev is None else 0.5 * prev + 0.5 * dt
                )

    def submit(self, spec_dict: dict) -> Job:
        """Parse, validate, and enqueue one job (raises ValueError /
        KeyError on a malformed spec, QueueFullError on backpressure).
        This is where the job's run_id is minted (Job.__post_init__) —
        the first flight-recorder event of the run is its admission.
        While draining, admission refuses with DrainingError (the HTTP
        layer maps it to 503 + Retry-After)."""
        self._check_admission()
        spec = JobSpec.from_dict(spec_dict)
        job = Job(spec=spec, compat=self.pre_key(spec),
                  priority=spec.priority)
        try:
            self.queue.submit(
                job, retry_after_s=self.retry_after_s(job.compat)
            )
        except QueueFullError as e:
            self.recorder.record(
                "admission-rejected", ctx=_job_ctx(job),
                protocol=spec.protocol, depth=e.depth,
                retry_after_s=e.retry_after_s,
            )
            raise
        self.recorder.record(
            "admission", ctx=_job_ctx(job),
            protocol=spec.protocol, compat=job.compat,
            sim_ms=spec.sim_ms, chunk_ms=spec.chunk_ms or None,
            schedule_units=len(self._schedule_for(spec)),
            priority=spec.priority or None,
            queue_depth=self.queue.depth(),
        )
        self.metrics.observe_submit()
        if self.auto_start:
            self.start()
        return job

    def submit_legacy(self, thunk, priority: int = 0) -> Job:
        """Queue an opaque host-side thunk (the rerouted /w/sweep and
        the legacy runMs gateway): it occupies one lane turn and is
        never packed with batch jobs."""
        self._check_admission()
        job = Job(spec=None, compat="", kind="legacy", thunk=thunk,
                  priority=priority)
        job.compat = f"legacy-{job.id}"
        self.queue.submit(job, retry_after_s=self.retry_after_s())
        self.metrics.observe_submit()
        if self.auto_start:
            self.start()
        return job

    def cancel(self, job_id: str) -> Job:
        job, cancelled_now = self.queue.cancel(job_id)
        if cancelled_now:
            self.metrics.observe_job(job)
            self.queue.retire(job)
        return job

    # -- families ------------------------------------------------------

    @staticmethod
    def _params_key(params: dict) -> str:
        return json.dumps(params, sort_keys=True, default=str)

    def family_for(self, spec: JobSpec) -> ScenarioFamily:
        key = self.pre_key(spec)
        with self._fam_lock:
            fam = self._families.get(key)
            if fam is not None:
                return fam
            from ..runtime.supervisor import stable_run_key
            from ..telemetry import TelemetryConfig

            schedule = self._schedule_for(spec)
            chunked = self._is_chunked(spec)
            unit = schedule[0]
            snaps = self.telemetry_snapshots
            # snapshot cadence must derive from the family's traced
            # geometry: the chunk UNIT for chunked families (whose
            # members disagree on sim_ms), the horizon for direct ones
            cadence_ms = unit if chunked else spec.sim_ms
            tele_cfg = TelemetryConfig(
                snapshots=snaps,
                snapshot_every_ms=max(1, cadence_ms // max(1, snaps)),
            )
            entry = serve_protocol(spec.protocol)
            net, state = entry.build(spec.params, tele_cfg)
            # faults are ALWAYS armed: a fault-free job is the neutral
            # schedule (bit-identical by the SL406 contract), so one
            # program serves faulted and clean rows alike
            net, state = net.with_faults(state)
            # chunked families span horizons, so the digest carries the
            # unit (n_chunks=0 marks "variable"); direct families keep
            # the single-call geometry
            digest = key + "/" + stable_run_key(
                net, state, 0 if chunked else 1,
                unit if chunked else spec.sim_ms,
            )
            fam = ScenarioFamily(
                key, digest, net, entry, tele_cfg, spec.sim_ms,
                unit if chunked else 0, self._params_key(spec.params),
                state, mode="chunked" if chunked else "direct",
                unit_ms=unit,
            )
            self._families[key] = fam
            return fam

    def _template_for(self, fam: ScenarioFamily, spec: JobSpec):
        pk = self._params_key(spec.params)
        st = fam.templates.get(pk)
        if st is not None:
            return st
        # params differ only in state-only fields (same pre-key): build
        # the layout with a throwaway engine, arm side-cars through the
        # FAMILY net so the signature discipline is identical, and keep
        # only the state
        _, st = fam.entry.build(spec.params, fam.tele_cfg)
        _, st = fam.net.with_faults(st)
        if _leaf_signature(st) != fam.signature:
            raise ValueError(
                f"params {spec.params} change the traced program despite "
                f"matching family {fam.key} — state-only contract "
                "violation (simlint SL801)"
            )
        fam.templates[pk] = st
        return st

    def _row(self, fam: ScenarioFamily, spec: JobSpec):
        st = self._template_for(fam, spec)
        # seed is per-replica data; `*0 +` keeps the leaf dtype exact
        return st._replace(seed=st.seed * 0 + spec.seed)

    def _pack(self, fam: ScenarioFamily, jobs: List[Job]):
        """Stack job rows + padding to the fixed replica capacity and
        attach the per-row fault schedules.  The padding rows are the
        base template (results discarded): every batch of a family has
        the identical leaf signature -> one compile, ever."""
        from ..engine import stack_states
        from ..faults.plan import lower_plans

        base = next(iter(fam.templates.values()))
        rows = [self._row(fam, j.spec) for j in jobs]
        rows += [base] * (self.max_batch_replicas - len(rows))
        stacked = stack_states(rows)
        plans = [j.spec.plan for j in jobs]
        plans += [None] * (self.max_batch_replicas - len(plans))
        fs = lower_plans(
            plans, fam.net.n_nodes, fam.net.protocol.n_msg_types()
        )
        return stacked._replace(faults=fs)

    # -- results -------------------------------------------------------

    def _row_result(self, fam: ScenarioFamily, row) -> dict:
        import numpy as np

        from ..telemetry.export import counters, progress_series

        return {
            "digest": state_digest(row),
            "time": int(np.asarray(row.time)),
            "counters": counters(fam.net, row),
            "progress": progress_series(row),
        }

    def run_singleton(self, spec_dict: dict) -> dict:
        """Reference result for one spec: a 1-row stack through the
        engine directly (no packing, no run cache).  The multi-tenant
        contract is that every batched job's result digest equals this
        — rows of a vmapped run are lane-independent.  A chunked or
        horizon-sharded spec replays the SAME chunk schedule
        (jobs.chunk_schedule — one source of truth with the batched
        path): the sim state is schedule-independent, but the telemetry
        loop census (jumps cannot cross a chunk boundary) is part of
        the digested side-car."""
        import jax

        from ..engine import stack_states
        from ..faults.plan import lower_plans

        spec = JobSpec.from_dict(spec_dict)
        fam = self.family_for(spec)
        row = self._row(fam, spec)
        stacked = stack_states([row])
        fs = lower_plans(
            [spec.plan], fam.net.n_nodes, fam.net.protocol.n_msg_types()
        )
        out = stacked._replace(faults=fs)
        for step in self._schedule_for(spec):
            out = fam.net.run_ms_batched(out, step)
        single = jax.tree_util.tree_map(lambda a: a[0], out)
        return self._row_result(fam, single)

    # -- planning (also the simlint SL801 surface) ---------------------

    def plan_batches(self) -> List[dict]:
        """Group the pending queue into dispatch plans WITHOUT removing
        or running anything: highest-priority-first, FIFO within a
        family, capped at the replica capacity.  Every plan's jobs share
        one compat key by construction — the property simlint's
        scheduler-contract pass verifies against the full static
        digests."""
        remaining = sorted(
            self.queue.pending_snapshot(),
            key=lambda j: (-j.priority, j.seq),
        )
        plans = []
        while remaining:
            head = remaining[0]
            same = [j for j in remaining if j.compat == head.compat]
            take = same[: self.max_batch_replicas]
            taken = set(id(j) for j in take)
            remaining = [j for j in remaining if id(j) not in taken]
            plans.append(
                {
                    "compat": head.compat,
                    "priority": head.priority,
                    "jobs": [j.id for j in take],
                    "kind": head.kind,
                }
            )
        return plans

    # -- dispatch ------------------------------------------------------

    def _lane_obj(self, lane: Optional[int]) -> _Lane:
        return self._lanes[0 if lane is None else lane]

    def _claimable_pending(self, lane_idx: int) -> Optional[Job]:
        """Best pending job this lane may run: legacy thunks run
        anywhere; batch jobs only where their family is (or can be)
        bound.  Caller holds the dispatch lock."""
        best = None
        for j in self.queue.pending_snapshot():
            bound = self._family_lane.get(j.compat)
            if j.kind != "legacy" and bound is not None and bound != lane_idx:
                continue
            if best is None or (j.priority, -j.seq) > (
                best.priority, -best.seq
            ):
                best = j
        return best

    def _claim(self, lane: _Lane):
        """One scheduling decision for one lane, under the dispatch
        lock: resume this lane's best parked batch or pop the best
        claimable pending group (binding its family to the lane).
        Returns ("parked", batch) | ("legacy", job) | ("jobs", jobs) |
        None.  While draining nothing is claimable: pending jobs stay
        queued and parked batches stay checkpoint-parked."""
        if self._draining.is_set():
            return None
        yield_point("serve.claim")
        with self._dispatch_lock:
            parked = max(
                (
                    b
                    for b in self._parked
                    if not b.running and b.lane == lane.index
                ),
                key=lambda b: (b.priority, -b.started),
                default=None,
            )
            best = self._claimable_pending(lane.index)
            if parked is not None and (
                best is None or best.priority <= parked.priority
            ):
                parked.running = True
                self._binding_used[parked.family.key] = time.monotonic()
                self._mark_busy(lane)
                return ("parked", parked)
            if best is None:
                return None
            if parked is not None and best.priority > parked.priority:
                if not parked.preempted:
                    parked.preempted = True
                    self.metrics.observe_preemption()
            jobs = self.queue.take_batch(
                best.compat,
                1 if best.kind == "legacy" else self.max_batch_replicas,
            )
            if not jobs:
                return None
            if best.kind == "legacy":
                self._mark_busy(lane)
                return ("legacy", jobs[0])
            # sticky family→lane binding: placement is part of the
            # compiled program's signature, so a family that wandered
            # across lanes would compile once per lane
            self._family_lane.setdefault(best.compat, lane.index)
            self._binding_used[best.compat] = time.monotonic()
            self._mark_busy(lane)
            return ("jobs", jobs)

    def _mark_busy(self, lane: _Lane) -> None:
        """Caller holds the dispatch lock.  Wave width = lanes busy the
        instant this dispatch starts (this lane included)."""
        lane.busy = True
        lane.dispatches += 1
        self._active_dispatches += 1
        width = sum(1 for l in self._lanes if l.busy)
        self.metrics.observe_wave(lane.index, width)

    def _mark_idle(self, lane: _Lane, t0: float) -> None:
        with self._dispatch_lock:
            lane.busy = False
            lane.busy_seconds += time.monotonic() - t0
            self._active_dispatches -= 1

    def drain_once(self, lane: Optional[int] = None) -> bool:
        """One scheduling decision on one lane (default: lane 0 — the
        deterministic entry point tests drive; each lane's worker loop
        calls this with its own index).  Returns False when this lane
        has nothing claimable."""
        lane_obj = self._lane_obj(lane)
        claim = self._claim(lane_obj)
        if claim is None:
            return False
        kind, item = claim
        t0 = time.monotonic()
        try:
            if kind == "parked":
                return self._continue_parked(item)
            if kind == "legacy":
                self._run_legacy(item)
                return True
            self._dispatch(item, lane_obj)
            return True
        finally:
            self._mark_idle(lane_obj, t0)

    def _finish_job(self, job: Job, state: JobState, **kw) -> None:
        job.finish(state, **kw)
        self.metrics.observe_job(job)
        self.queue.retire(job)

    def _run_legacy(self, job: Job) -> None:
        job.state = JobState.RUNNING
        job.started_at = time.monotonic()
        if job.cancel_requested:
            self._finish_job(job, JobState.CANCELLED)
            return
        try:
            result = job.thunk()
        except BaseException as e:  # noqa: BLE001 — surfaced to waiter
            self._finish_job(
                job, JobState.FAILED,
                error=f"{type(e).__name__}: {e}", exc=e,
            )
            return
        self._finish_job(job, JobState.DONE, result=result)

    def _dispatch(self, jobs: List[Job], lane: _Lane) -> None:
        yield_point("serve.dispatch")
        live = []
        for j in jobs:
            if j.cancel_requested:
                self._finish_job(j, JobState.CANCELLED)
            else:
                live.append(j)
        if not live:
            return
        # scheduler contract (simlint SL801): one batch, one digest
        compat = {j.compat for j in live}
        if len(compat) != 1:
            raise RuntimeError(
                f"batch mixes compatibility keys {sorted(compat)}"
            )
        try:
            fam = self.family_for(live[0].spec)
        except BaseException as e:  # noqa: BLE001 — family build failure
            # the family comes from the shared pre-key, so no single
            # job can be blamed: the whole group fails honestly
            for j in live:
                self._finish_job(
                    j, JobState.FAILED,
                    error=f"{type(e).__name__}: {e}",
                    error_kind=classify(e), exc=e,
                )
            return
        # per-row blast-radius control: a job whose OWN row fails to
        # build (bad state-only params, SL801 violation) is quarantined
        # alone instead of failing every rider in the batch
        ok = []
        for j in live:
            try:
                self._row(fam, j.spec)
            except BaseException as e:  # noqa: BLE001 — poison row build
                self._quarantine(j, e, phase="row-build")
            else:
                ok.append(j)
        live = ok
        if not live:
            return
        try:
            stacked = self._pack(fam, live)
        except BaseException as e:  # noqa: BLE001 — pack failure
            for j in live:
                self._finish_job(
                    j, JobState.FAILED,
                    error=f"{type(e).__name__}: {e}",
                    error_kind=classify(e), exc=e,
                )
            return
        with self._dispatch_lock:
            self._batch_seq += 1
            batch_id = f"batch-{self._batch_seq:05d}"
            wave_width = sum(1 for l in self._lanes if l.busy)
        now = time.monotonic()
        for j in live:
            j.state = JobState.RUNNING
            j.started_at = now
            j.batch_id = batch_id
        # the batch gets its own run identity (it IS the device run);
        # the pack event records the join batch run_id <-> member job
        # run_ids, so obs_query can walk from any job to its chunks
        batch_ctx = mint_context("batch")
        if lane is not None:
            lane.last_ctx = batch_ctx
        self.recorder.record(
            "pack", ctx=batch_ctx, batch_id=batch_id,
            compat=live[0].compat, family_digest=fam.digest,
            mode=fam.mode,
            lane=lane.index,
            wave_width=wave_width,
            members=[
                {
                    "job_id": j.id,
                    "run_id": j.run_id,
                    "tenant": j.spec.tenant,
                    "replica": i,
                    "sim_ms": j.spec.sim_ms,
                }
                for i, j in enumerate(live)
            ],
            live_rows=len(live),
            padding_rows=self.max_batch_replicas - len(live),
            capacity=self.max_batch_replicas,
        )
        if fam.mode == "chunked":
            self._start_chunked(
                batch_id, fam, live, stacked, batch_ctx, lane
            )
        else:
            self._dispatch_direct(
                batch_id, fam, live, stacked, batch_ctx, lane
            )

    def _dispatch_direct(
        self, batch_id, fam, jobs, stacked, ctx=None, lane=None
    ) -> None:
        from ..parallel.replica_shard import sharded_run_stats

        if lane is not None and lane.group is not None:
            # commit the batch to this lane's devices: wave packing's
            # concurrency comes from different lanes running on
            # disjoint device groups; with a 2D lane the engine hands
            # place() the node-column classification
            stacked = lane.group.place(stacked, net=fam.net)
        t0 = time.monotonic()
        try:
            self._chaos_check(fam, jobs)
            out, _stats = sharded_run_stats(fam.net, stacked, fam.sim_ms)
            self._finalize(fam, jobs, out)
        except BaseException as e:  # noqa: BLE001 — device failure
            self.recorder.record(
                "batch-failed", ctx=ctx, batch_id=batch_id,
                error=f"{type(e).__name__}: {e}"[:500],
            )
            self._salvage_batch(
                fam, jobs, self._direct_salvage_runner(fam, lane),
                batch_id, ctx, e,
            )
            return
        finally:
            dt = time.monotonic() - t0
            self._note_batch_time(jobs[0].compat if jobs else None, dt)
            self.metrics.observe_batch(
                len(jobs), self.max_batch_replicas, dt
            )

    def _row_watch(self, fam: ScenarioFamily, jobs: List[Job]):
        """Done-row census callback for the Supervisor's per-chunk sync
        (runtime.supervisor row_watch): counts member rows whose
        protocol all_done already holds — the observability signal the
        harvesting lever is judged by.  Reads the already-synced state
        only; never feeds back into the sim."""
        import jax
        import numpy as np

        proto = fam.net.protocol
        n_live = len(jobs)

        def watch(state, chunk):
            done = np.asarray(jax.vmap(proto.all_done)(state))
            self.metrics.observe_rows_done(
                int(done[:n_live].sum()), n_live
            )

        return watch

    def _build_supervisor(
        self, batch_id, fam, jobs, stacked, capacity, n_chunks,
        ckpt_dir, ctx, lane,
    ):
        """One chunked-batch Supervisor (shared by the pack path and
        done-row harvesting, which re-parks survivors under a smaller
        capacity).  The chunk function goes through the run cache:
        chunked mode costs ONE extra compile per family geometry, not
        one per slice."""
        from ..parallel.replica_shard import _run_and_reduce
        from ..runtime.supervisor import Supervisor, stable_run_key

        unit = fam.unit_ms
        cached = _run_and_reduce(fam.net, unit)
        placement = (
            (lambda s, _g=lane.group, _n=fam.net: _g.place(s, net=_n))
            if lane is not None and lane.group is not None
            else None
        )
        # a fresh sentinel per batch: the per-invariant alert latch is
        # per-run, and its violations alert through the scheduler's SLO
        # engine (typed event + witt_obs_alerts_total) naming this
        # batch's run_id
        sentinel = InvariantSentinel(
            net=fam.net,
            capacity_table=self._capacity_table,
            engine=self.slo,
        )
        return Supervisor(
            lambda s: cached(s)[0],
            stacked,
            n_chunks=n_chunks,
            chunk_ms=unit,
            checkpoint_dir=ckpt_dir,
            checkpoint_every=1,
            run_key=stable_run_key(fam.net, stacked, n_chunks, unit),
            max_chunks_this_run=self.slice_chunks,
            ctx=ctx,
            recorder=self.recorder,
            placement=placement,
            timeseries=self.metrics.timeseries,
            sentinel=sentinel,
            row_watch=self._row_watch(fam, jobs),
            # graceful drain: an in-flight slice stops at its next
            # chunk boundary (checkpoint on disk), batch stays parked
            should_stop=self._draining.is_set,
            run_meta={
                "batch_id": batch_id,
                "capacity": capacity,
                "members": [
                    {"job_id": j.id, "run_id": j.run_id,
                     "tenant": j.spec.tenant}
                    for j in jobs
                ],
            },
        )

    def _start_chunked(
        self, batch_id, fam, jobs, stacked, ctx=None, lane=None
    ) -> None:
        unit = fam.unit_ms
        # horizon sharding: every member advances in the same fixed
        # units; its OWN chunk count (and quantum remainder) decides
        # when its row is captured
        job_chunks = [max(1, j.spec.sim_ms // unit) for j in jobs]
        job_rems = [
            j.spec.sim_ms % unit if j.spec.sim_ms > unit else 0
            for j in jobs
        ]
        n_chunks = max(job_chunks)
        ckpt_dir = os.path.join(self.checkpoint_root, batch_id)
        sup = self._build_supervisor(
            batch_id, fam, jobs, stacked, self.max_batch_replicas,
            n_chunks, ckpt_dir, ctx, lane,
        )
        parked = _ParkedBatch(
            batch_id, fam, jobs, sup, ckpt_dir,
            max(j.priority for j in jobs), self.max_batch_replicas,
            lane=lane.index if lane is not None else 0,
            job_chunks=job_chunks, job_rems=job_rems,
        )
        parked.running = True
        with self._dispatch_lock:
            self._parked.append(parked)
        self._continue_parked(parked)

    def _continue_parked(self, parked: _ParkedBatch) -> bool:
        try:
            if parked.preempted:
                parked.preempted = False
                self.metrics.observe_resume()
            if all(j.cancel_requested for j in parked.jobs):
                for j in parked.jobs:
                    if j.id not in parked.finished:
                        self._finish_job(j, JobState.CANCELLED)
                self._drop_parked(parked)
                return True
            # stop exactly at the next member horizon boundary (where
            # finished rows are captured) without exceeding the
            # preemption slice
            next_boundary = min(
                (c for c in parked.job_chunks if c > parked.chunks_done),
                default=parked.supervisor.n_chunks,
            )
            parked.supervisor.max_chunks_this_run = min(
                self.slice_chunks, next_boundary - parked.chunks_done
            )
            t0 = time.monotonic()
            try:
                self._chaos_check(parked.family, parked.jobs)
                report = parked.supervisor.run()
            except BaseException as e:  # noqa: BLE001 — supervised failure
                # the supervisor already recorded + dumped its black
                # box; this event marks the batch-level consequence
                self.recorder.record(
                    "batch-failed", ctx=parked.supervisor.ctx,
                    batch_id=parked.batch_id,
                    error=f"{type(e).__name__}: {e}"[:500],
                )
                survivors = [
                    j for j in parked.jobs if j.id not in parked.finished
                ]
                lane = self._lanes[parked.lane]
                self._drop_parked(parked)
                self._salvage_batch(
                    parked.family, survivors,
                    self._chunked_salvage_runner(parked.family, lane),
                    parked.batch_id, parked.supervisor.ctx, e,
                )
                return True
            dt = time.monotonic() - t0
            self._note_batch_time(parked.family.key, dt)
            self.metrics.observe_batch(
                len(parked.jobs), parked.capacity, dt
            )
            parked.chunks_done = report.chunks_done
            self._stream_progress(parked, report.state)
            self._capture_finished(parked, report.state)
            if report.ok or len(parked.finished) == len(parked.jobs):
                self._drop_parked(parked)
            elif self.harvest:
                # survivors may now fit a smaller capacity bucket: the
                # per-chunk sync already materialized report.state on
                # host, so compaction costs one gather, not a sync
                self._maybe_harvest(parked, report.state)
            # otherwise: a controlled partial stop — the batch stays
            # parked (checkpoint on disk) and this lane's next
            # drain_once decides whether it continues or yields to
            # higher-priority work
            return True
        finally:
            parked.running = False

    def _harvest_bucket(self, survivors: int, capacity: int,
                        lane: _Lane) -> Optional[int]:
        """Smallest power-of-two replica width that (a) holds the
        survivors, (b) divides evenly over the lane's devices when one
        is placed, and (c) is strictly smaller than the current
        capacity — None when compaction buys nothing."""
        b = 1
        while b < survivors:
            b <<= 1
        if lane is not None and lane.group is not None:
            nd = len(lane.group.devices)
            while b < nd or b % nd:
                b <<= 1
        return b if b < capacity else None

    def _maybe_harvest(self, parked: _ParkedBatch, stacked) -> None:
        """Done-row harvesting (ISSUE 18): compact the survivors of a
        partially-finished parked batch into the next-smaller capacity
        bucket and re-park them under a fresh Supervisor, so later
        slices stop re-running rows that already finalized at their
        horizon boundary.

        Per-row bitwise identity is the salvage-bisection argument:
        vmap rows are independent, so a survivor's row carried (one
        gather, no recompute) into a narrower stack continues its exact
        singleton stream; chunk boundaries are unchanged (the rebased
        supervisor still steps the same fixed units), and the padding
        rows duplicate a survivor (results discarded, like _pack's
        base-template rows).  Compile discipline: the narrower width is
        ONE new input geometry inside the family's existing run-cache
        entry, compiled once ever and published to the compile store —
        the mixed-workload compile pin holds."""
        yield_point("serve.harvest")
        import jax
        import numpy as np

        surv = [
            i for i, j in enumerate(parked.jobs)
            if j.id not in parked.finished
        ]
        if not surv:
            return
        lane = self._lanes[parked.lane]
        bucket = self._harvest_bucket(len(surv), parked.capacity, lane)
        if bucket is None:
            return
        idx = np.asarray(
            surv + [surv[0]] * (bucket - len(surv)), np.int32
        )
        compacted = jax.tree_util.tree_map(lambda a: a[idx], stacked)
        jobs = [parked.jobs[i] for i in surv]
        job_chunks = [
            parked.job_chunks[i] - parked.chunks_done for i in surv
        ]
        job_rems = [parked.job_rems[i] for i in surv]
        batch_id = f"{parked.batch_id}-h{bucket}"
        ckpt_dir = os.path.join(self.checkpoint_root, batch_id)
        ctx = mint_context("batch")
        try:
            sup = self._build_supervisor(
                batch_id, parked.family, jobs, compacted, bucket,
                max(job_chunks), ckpt_dir, ctx, lane,
            )
        except BaseException as e:  # noqa: BLE001 — keep the wide batch
            # harvesting is an optimization: on any failure the batch
            # stays parked at its current width and resumes as before
            self.recorder.record(
                "harvest-failed", ctx=ctx, batch_id=parked.batch_id,
                error=f"{type(e).__name__}: {e}"[:500],
            )
            return
        fresh = _ParkedBatch(
            batch_id, parked.family, jobs, sup, ckpt_dir,
            max(j.priority for j in jobs), bucket, lane=parked.lane,
            job_chunks=job_chunks, job_rems=job_rems,
        )
        fresh.preempted = parked.preempted
        for j in jobs:
            j.batch_id = batch_id
        self.recorder.record(
            "harvest", ctx=ctx, batch_id=parked.batch_id,
            harvested_batch_id=batch_id,
            survivors=len(surv),
            capacity_before=parked.capacity, capacity_after=bucket,
            chunks_done=parked.chunks_done,
            members=[
                {"job_id": j.id, "run_id": j.run_id} for j in jobs
            ],
        )
        with self._dispatch_lock:
            if parked in self._parked:
                self._parked.remove(parked)
            self._parked.append(fresh)
        shutil.rmtree(parked.ckpt_dir, ignore_errors=True)
        self.metrics.observe_harvest(parked.capacity - bucket, ctx=ctx)

    def _capture_finished(self, parked: _ParkedBatch, stacked) -> None:
        """Finalize every member whose horizon boundary is the current
        chunk count: capture its row from the batch state, run any
        quantum remainder on a 1-row stack (the singleton replays the
        identical [unit]*k + [rem] schedule), and finish the job while
        the batch runs on for longer-horizon members."""
        import jax

        fam = parked.family
        finishing = [
            i
            for i, c in enumerate(parked.job_chunks)
            if c == parked.chunks_done
            and parked.jobs[i].id not in parked.finished
        ]
        if not finishing:
            return
        attrib = self._attribution(fam, parked.jobs, stacked)
        for i in finishing:
            job = parked.jobs[i]
            parked.finished.add(job.id)
            if job.cancel_requested:
                self._finish_job(job, JobState.CANCELLED)
                continue
            rem = parked.job_rems[i]
            try:
                if rem:
                    row = self._run_remainder(fam, stacked, i, rem)
                else:
                    row = jax.tree_util.tree_map(
                        lambda a, i=i: a[i], stacked
                    )
                result = self._row_result(fam, row)
            except BaseException as e:  # noqa: BLE001 — row finalization
                self._finish_job(
                    job, JobState.FAILED,
                    error=f"{type(e).__name__}: {e}", exc=e,
                )
                continue
            job.progress = result["progress"]
            if attrib is not None:
                job.attribution = self._job_attribution(attrib, job)
                result["attribution"] = job.attribution
                self.metrics.observe_tenant(
                    job.spec.tenant, attrib["jobs"].get(job.id)
                )
            self._finish_job(job, JobState.DONE, result=result)

    def _run_remainder(self, fam: ScenarioFamily, stacked, i: int,
                       rem_ms: int):
        """A quantum remainder (sim_ms % unit) for one captured row: a
        1-row stack through the run cache — the tail of the same chunk
        schedule the singleton replays.  Costs one small compiled
        program per distinct remainder length."""
        import jax

        from ..parallel.replica_shard import _run_and_reduce

        row1 = jax.tree_util.tree_map(lambda a, i=i: a[i : i + 1], stacked)
        out, _stats = _run_and_reduce(fam.net, rem_ms)(row1)
        return jax.tree_util.tree_map(lambda a: a[0], out)

    def _stream_progress(self, parked: _ParkedBatch, stacked) -> None:
        from ..telemetry.export import progress_series

        # live per-tenant attribution at the slice boundary: /w/jobs
        # shows who is consuming the batch while it runs, not only at
        # the end
        attrib = self._attribution(parked.family, parked.jobs, stacked)
        for i, job in enumerate(parked.jobs):
            if job.state is not JobState.RUNNING:
                continue
            if attrib is not None:
                job.attribution = self._job_attribution(attrib, job)
            series = progress_series(stacked, replica=i)
            if series:
                job.progress = series
                self.metrics.observe_ttfr(job)

    def _drop_parked(self, parked: _ParkedBatch) -> None:
        with self._dispatch_lock:
            if parked in self._parked:
                self._parked.remove(parked)
        shutil.rmtree(parked.ckpt_dir, ignore_errors=True)

    # -- attribution ----------------------------------------------------

    def _attribution(self, fam, jobs: List[Job], out) -> Optional[dict]:
        """Per-tenant counter slice of a packed batch (obs module);
        read-only over the final state, never affects the result
        digests."""
        try:
            return batch_attribution(
                fam.net,
                out,
                [
                    {"job_id": j.id, "run_id": j.run_id,
                     "tenant": j.spec.tenant}
                    for j in jobs
                ],
                self.max_batch_replicas,
            )
        except (TypeError, ValueError, AttributeError):
            return None  # attribution must never fail a batch

    @staticmethod
    def _job_attribution(attrib: dict, job: Job) -> dict:
        """The one-job status view: this job's row slice, its tenant's
        aggregate, and the batch totals they reconcile against."""
        return {
            "job": attrib["jobs"].get(job.id),
            "tenant": attrib["tenants"].get(job.spec.tenant),
            "batch": attrib["batch"],
        }

    def _finalize(self, fam: ScenarioFamily, jobs: List[Job], out) -> None:
        import jax

        attrib = self._attribution(fam, jobs, out)
        for i, job in enumerate(jobs):
            if job.cancel_requested:
                self._finish_job(job, JobState.CANCELLED)
                continue
            row = jax.tree_util.tree_map(lambda a, i=i: a[i], out)
            result = self._row_result(fam, row)
            job.progress = result["progress"]
            if attrib is not None:
                job.attribution = self._job_attribution(attrib, job)
                result["attribution"] = job.attribution
                self.metrics.observe_tenant(
                    job.spec.tenant, attrib["jobs"].get(job.id)
                )
            self._finish_job(job, JobState.DONE, result=result)

    # -- poison quarantine + batch salvage ------------------------------

    def _chaos_check(self, fam: ScenarioFamily, jobs: List[Job]) -> None:
        if self.chaos_injector is not None:
            self.chaos_injector(fam, jobs)

    def _quarantine(self, job: Job, cause: BaseException,
                    phase: str = "salvage") -> None:
        """Terminal 4xx-style disposition: this job's OWN row breaks the
        batch, so it must never be packed (or retried) again."""
        perr = PoisonRowError(job.id, cause)
        kind = classify(perr)
        self.recorder.record(
            "quarantine", ctx=_job_ctx(job), job_id=job.id,
            batch_id=job.batch_id, phase=phase,
            error=str(perr)[:300],
        )
        self._finish_job(
            job, JobState.QUARANTINED,
            error=str(perr), error_kind=kind, exc=perr,
        )

    def _direct_salvage_runner(self, fam: ScenarioFamily, lane=None):
        """Re-run a subset of a failed direct batch.  Padding to the
        SAME replica capacity keeps the leaf signature identical, so a
        probe is a run-cache hit on the family's one compiled program;
        vmap row-independence makes each survivor's result bitwise
        identical to its singleton."""
        from ..parallel.replica_shard import sharded_run_stats

        def run(subset: List[Job]) -> None:
            stacked = self._pack(fam, subset)
            if lane is not None and lane.group is not None:
                stacked = lane.group.place(stacked, net=fam.net)
            self._chaos_check(fam, subset)
            out, _stats = sharded_run_stats(fam.net, stacked, fam.sim_ms)
            self._finalize(fam, subset, out)

        return run

    def _chunked_salvage_runner(self, fam: ScenarioFamily, lane=None):
        """Re-run a subset of a failed chunked batch from chunk 0,
        replaying the shared unit schedule (jobs.chunk_schedule) and
        capturing each row at its own horizon boundary — the identical
        schedule the singleton replays, so survivors stay bitwise."""
        import jax

        from ..parallel.replica_shard import _run_and_reduce

        unit = fam.unit_ms

        def run(subset: List[Job]) -> None:
            job_chunks = [
                max(1, j.spec.sim_ms // unit) for j in subset
            ]
            job_rems = [
                j.spec.sim_ms % unit if j.spec.sim_ms > unit else 0
                for j in subset
            ]
            stacked = self._pack(fam, subset)
            if lane is not None and lane.group is not None:
                stacked = lane.group.place(stacked, net=fam.net)
            self._chaos_check(fam, subset)
            cached = _run_and_reduce(fam.net, unit)
            rows = {}
            for step in range(1, max(job_chunks) + 1):
                stacked = cached(stacked)[0]
                for i, j in enumerate(subset):
                    if job_chunks[i] != step:
                        continue
                    rem = job_rems[i]
                    rows[j.id] = (
                        self._run_remainder(fam, stacked, i, rem)
                        if rem
                        else jax.tree_util.tree_map(
                            lambda a, i=i: a[i], stacked
                        )
                    )
            attrib = self._attribution(fam, subset, stacked)
            for j in subset:
                if j.cancel_requested:
                    self._finish_job(j, JobState.CANCELLED)
                    continue
                result = self._row_result(fam, rows[j.id])
                j.progress = result["progress"]
                if attrib is not None:
                    j.attribution = self._job_attribution(attrib, j)
                    result["attribution"] = j.attribution
                    self.metrics.observe_tenant(
                        j.spec.tenant, attrib["jobs"].get(j.id)
                    )
                self._finish_job(j, JobState.DONE, result=result)

        return run

    def _salvage_batch(self, fam: ScenarioFamily, jobs: List[Job],
                       runner, batch_id, ctx,
                       error: BaseException) -> None:
        """Bisect a failed batch to isolate the poison row(s).

        A passing probe's results are KEPT (same compiled program, rows
        lane-independent under vmap → bitwise identical to singletons);
        a failing probe splits in half; a failing singleton probe is the
        poison and is QUARANTINED — unless its failure classifies as
        retryable (transient/device_lost), where blaming the job would
        be dishonest, so it FAILS with the taxonomy kind instead.  When
        the probe budget (SalvagePolicy.max_probe_runs) runs out,
        unresolved rows FAIL with the original batch error rather than
        guess."""
        err_s = f"{type(error).__name__}: {error}"
        if not self.salvage.enabled:
            for j in jobs:
                self._finish_job(
                    j, JobState.FAILED, error=err_s,
                    error_kind=classify(error), exc=error,
                )
            return
        t0 = time.monotonic()
        self.recorder.record(
            "salvage-start", ctx=ctx, batch_id=batch_id,
            rows=len(jobs), error=err_s[:300],
        )
        runs = 0
        quarantined: List[tuple] = []
        failed: List[tuple] = []

        def probe(subset: List[Job]) -> None:
            nonlocal runs
            if runs >= self.salvage.max_probe_runs:
                failed.extend((j, error) for j in subset)
                return
            runs += 1
            try:
                runner(subset)  # finalizes DONE/CANCELLED on success
            except BaseException as e:  # noqa: BLE001 — probe failure
                self.recorder.record(
                    "salvage-run", ctx=ctx, batch_id=batch_id,
                    rows=len(subset), ok=False,
                    error=f"{type(e).__name__}: {e}"[:300],
                )
                if len(subset) == 1:
                    if classify(e) in RETRYABLE_KINDS:
                        failed.append((subset[0], e))
                    else:
                        quarantined.append((subset[0], e))
                    return
                mid = len(subset) // 2
                probe(subset[:mid])
                probe(subset[mid:])
            else:
                self.recorder.record(
                    "salvage-run", ctx=ctx, batch_id=batch_id,
                    rows=len(subset), ok=True,
                )

        if len(jobs) == 1:
            # singleton batch: one probe doubles as the transient retry
            probe(jobs)
        else:
            mid = len(jobs) // 2
            probe(jobs[:mid])
            probe(jobs[mid:])
        for j, cause in quarantined:
            if j.cancel_requested:
                self._finish_job(j, JobState.CANCELLED)
            else:
                self._quarantine(j, cause)
        for j, cause in failed:
            if j.cancel_requested:
                self._finish_job(j, JobState.CANCELLED)
                continue
            self._finish_job(
                j, JobState.FAILED,
                error=f"{type(cause).__name__}: {cause}",
                error_kind=classify(cause), exc=cause,
            )
        dt = time.monotonic() - t0
        self.metrics.observe_salvage(runs, dt)
        self.recorder.record(
            "salvage-done", ctx=ctx, batch_id=batch_id, runs=runs,
            seconds=round(dt, 4), quarantined=len(quarantined),
            failed=len(failed),
            salvaged=len(jobs) - len(quarantined) - len(failed),
        )

    # -- workers --------------------------------------------------------

    def start(self) -> None:
        # auto_start means every submit calls this: a burst of first
        # requests races the is_alive checks and, unguarded, each
        # spawns its own (identically named) workers — concurrent
        # workers on ONE lane then duplicate batch compiles.  One
        # worker per lane is the design; the dispatch lock serializes
        # their claims.
        with self._worker_lock:
            self._stop.clear()
            for lane in self._lanes:
                # explicit (re)start is an operator action: it pardons
                # lanes abandoned at the restart limit
                lane.abandoned = False
                if lane.thread is not None and lane.thread.is_alive():
                    continue
                lane.thread = threading.Thread(
                    target=self._loop, args=(lane.index,), daemon=True,
                    name=f"witt-serve-lane-{lane.index}",
                )
                lane.thread.start()

    def stop(self, timeout: float = 5.0) -> None:
        self._stop.set()
        self.queue.notify()
        with self._worker_lock:
            threads = [
                lane.thread
                for lane in self._lanes
                if lane.thread is not None
            ]
            for lane in self._lanes:
                lane.thread = None
        for t in threads:
            t.join(timeout)

    def _loop(self, lane_idx: int) -> None:
        lane = self._lanes[lane_idx]
        try:
            while not self._stop.is_set():
                if lane.kill_requested:
                    lane.kill_requested = False
                    raise LaneFailedError(lane_idx, "injected kill")
                if self.drain_once(lane_idx):
                    lane.fail_streak = 0
                else:
                    self._reap_bindings()
                    self.queue.wait_for_work(timeout=0.2)
        except BaseException as e:  # noqa: BLE001 — lane death
            if self._stop.is_set():
                return
            # per-job failures are reported on the jobs themselves; an
            # exception REACHING here killed the worker thread — treat
            # it as a lane failure: supervise, re-bind, restart
            self._on_lane_failure(lane, e)

    def inject_lane_failure(self, lane: int = 0) -> None:
        """Chaos hook: make the lane's worker raise LaneFailedError at
        its next loop iteration, exercising the REAL death → supervise →
        re-bind → restart path (a Python thread cannot be killed from
        outside, so the kill is cooperative but the recovery is not)."""
        self._lanes[lane].kill_requested = True
        self.queue.notify()

    def _on_lane_failure(self, lane: _Lane, exc: BaseException) -> None:
        """Fleet supervision, run on the dying thread as its last act:
        record and count the death, release any dispatch slot it held,
        re-bind its sticky families (and re-home its parked batches) to
        healthy lanes — or drop the bindings entirely in a single-lane
        fleet so the replacement worker re-binds on its next claim —
        then spawn the replacement with a crash-loop backoff."""
        yield_point("serve.lane-failure")
        kind = classify(exc)
        lane.fail_streak += 1
        victim = lane.last_ctx
        self.metrics.observe_lane_failure(ctx=victim)
        self.recorder.record(
            "lane-failed", ctx=victim, lane=lane.index, error_kind=kind,
            error=f"{type(exc).__name__}: {exc}"[:300],
            fail_streak=lane.fail_streak,
        )
        moved = []
        with self._dispatch_lock:
            if lane.busy:
                # died mid-dispatch bookkeeping: release the slot so
                # quiescence and wave-width stay truthful
                lane.busy = False
                self._active_dispatches = max(
                    0, self._active_dispatches - 1
                )
            healthy = [
                l for l in self._lanes
                if l is not lane and l.alive() and not l.abandoned
            ]
            if healthy:
                ring = itertools.cycle(healthy)
                for compat, idx in list(self._family_lane.items()):
                    if idx == lane.index:
                        tgt = next(ring).index
                        self._family_lane[compat] = tgt
                        moved.append((compat, tgt))
                for b in self._parked:
                    if b.lane == lane.index and not b.running:
                        b.lane = next(ring).index
            else:
                for compat, idx in list(self._family_lane.items()):
                    if idx == lane.index:
                        self._family_lane.pop(compat)
                        self._binding_used.pop(compat, None)
                        moved.append((compat, None))
        for compat, tgt in moved:
            self.metrics.observe_rebind()
            self.recorder.record(
                "family-rebound", compat=compat,
                from_lane=lane.index, to_lane=tgt,
            )
        self._restart_lane(lane)
        self.queue.notify()

    def _restart_lane(self, lane: _Lane) -> bool:
        if (
            self.lane_restart_limit
            and lane.restarts >= self.lane_restart_limit
        ):
            lane.abandoned = True
            self.recorder.record(
                "lane-abandoned", lane=lane.index,
                restarts=lane.restarts,
            )
            return False
        # crash-loop backoff paid by the dying thread — the rest of the
        # fleet keeps serving while this lane sits out
        time.sleep(min(1.0, 0.05 * lane.fail_streak))
        with self._worker_lock:
            if self._stop.is_set():
                return False
            lane.restarts += 1
            lane.thread = threading.Thread(
                target=self._loop, args=(lane.index,), daemon=True,
                name=f"witt-serve-lane-{lane.index}",
            )
            lane.thread.start()
        self.metrics.observe_lane_restart(ctx=lane.last_ctx)
        self.recorder.record(
            "lane-restart", ctx=lane.last_ctx, lane=lane.index,
            restarts=lane.restarts,
        )
        return True

    def _reap_bindings(self) -> None:
        """Expire sticky family→lane bindings that have had no queued
        job and no parked batch for ``binding_ttl_s`` (the PR-13 leak:
        bindings lived forever, so a retired family pinned its lane
        choice for the life of the process).  The family itself — and
        its compiled programs in the run cache — survives; only the
        lane decision is re-opened."""
        now = time.monotonic()
        expired = []
        with self._dispatch_lock:
            if not self._family_lane:
                return
            pending = {j.compat for j in self.queue.pending_snapshot()}
            parked = {b.family.key for b in self._parked}
            for compat in list(self._family_lane):
                if compat in pending or compat in parked:
                    continue
                last = self._binding_used.get(compat)
                if last is None:
                    # bound before use-stamping existed: start the
                    # clock now instead of expiring on sight
                    self._binding_used[compat] = now
                    continue
                if now - last >= self.binding_ttl_s:
                    self._family_lane.pop(compat)
                    self._binding_used.pop(compat, None)
                    expired.append(compat)
        for compat in expired:
            self.metrics.observe_binding_expired()
            self.recorder.record("binding-expired", compat=compat)

    def busy(self) -> bool:
        with self._dispatch_lock:
            active = self._active_dispatches
        return bool(self._parked) or self.queue.depth() > 0 or active > 0

    def wait_idle(self, timeout: float = 60.0) -> bool:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if not self.busy():
                return True
            time.sleep(0.02)
        return not self.busy()

    # -- graceful drain -------------------------------------------------

    def _check_admission(self) -> None:
        if self._draining.is_set():
            self.recorder.record("admission-rejected", reason="draining")
            raise DrainingError(self.retry_after_s())

    @property
    def draining(self) -> bool:
        return self._draining.is_set()

    def drain(self) -> dict:
        """Enter graceful drain: admission refuses with DrainingError
        (HTTP 503 + Retry-After), lanes stop claiming, and in-flight
        chunked slices checkpoint-stop at their next chunk boundary
        (the Supervisor's should_stop hook).  Pending jobs stay QUEUED
        and parked batches keep their checkpoints: undrain() resumes
        both, bit-identical (the supervisor's replay contract).
        Idempotent; returns drain_status()."""
        if not self._draining.is_set():
            self._draining.set()
            self.metrics.observe_drain()
            self.recorder.record(
                "drain-start", queue_depth=self.queue.depth(),
                parked=len(self._parked),
            )
        self.queue.notify()
        return self.drain_status()

    def undrain(self) -> dict:
        if self._draining.is_set():
            self._draining.clear()
            self.recorder.record(
                "drain-end", queue_depth=self.queue.depth(),
                parked=len(self._parked),
            )
        if self.auto_start:
            self.start()
        self.queue.notify()
        return self.drain_status()

    def quiescent(self) -> bool:
        """True once a drain has settled: no lane is executing anything
        (parked batches are checkpoints on disk, pending jobs are inert
        in the queue) — safe to stop the process."""
        with self._dispatch_lock:
            active = self._active_dispatches
        return self._draining.is_set() and active == 0

    def drain_status(self) -> dict:
        with self._dispatch_lock:
            active = self._active_dispatches
            parked = len(self._parked)
            draining = self._draining.is_set()
        return {
            "draining": draining,
            "quiescent": draining and active == 0,
            "activeDispatches": active,
            "parkedBatches": parked,
            "queueDepth": self.queue.depth(),
            "retryAfterS": self.retry_after_s(),
        }

    # -- exposition ----------------------------------------------------

    def health(self) -> dict:
        """Operational snapshot for /w/health and /w/ready: queue
        pressure, per-lane liveness, drain state, resilience counters,
        compile-store and error-taxonomy state.  Read-only."""
        from ..parallel.replica_shard import run_cache_info
        from ..runtime.compile_store import (
            compile_store_counters,
            get_compile_store,
        )
        from ..runtime.errors import taxonomy_counters
        from ..runtime.locks import lock_trace_status

        with self._dispatch_lock:
            lanes = [lane.describe() for lane in self._lanes]
            active = self._active_dispatches
            parked = len(self._parked)
            bindings = len(self._family_lane)
            draining = self._draining.is_set()
        store = get_compile_store()
        m = self.metrics
        # pull-driven SLO evaluation: every health poll refreshes the
        # burn-rate state (edge-triggered alerts fire here)
        self.slo.evaluate()
        lt = lock_trace_status()
        return {
            "queueDepth": self.queue.depth(),
            "queueCapacity": self.queue.max_depth,
            "draining": draining,
            "quiescent": draining and active == 0,
            "activeDispatches": active,
            "parkedBatches": parked,
            "families": len(self._families),
            "familyBindings": bindings,
            "lanes": lanes,
            "lanesAlive": sum(1 for d in lanes if d["alive"]),
            "laneFailuresTotal": m.lane_failures_total,
            "laneRestartsTotal": m.lane_restarts_total,
            "quarantinedTotal": m.jobs_quarantined,
            "salvageBatchesTotal": m.salvage_batches_total,
            "compileStore": {
                "enabled": store is not None,
                "counters": compile_store_counters(),
            },
            "runCache": run_cache_info(),
            "errorKinds": taxonomy_counters(),
            "alerts": self.slo.alert_counts(),
            "lockTrace": {
                k: lt[k]
                for k in ("armed", "maxWaitS", "waitP99S", "violationCount")
            },
        }

    def slo_status(self) -> dict:
        """The /w/slo payload: burn-rate rows per registered SLO,
        active (latched) alerts, alert counters, and the metric-history
        digest they are computed from.  Evaluating here means any
        poller keeps the alert state fresh (pull model — no evaluator
        thread to supervise)."""
        return self.slo.status(evaluate=True)

    def status(self) -> dict:
        return {
            "queueDepth": self.queue.depth(),
            "queueCapacity": self.queue.max_depth,
            "draining": self._draining.is_set(),
            "parkedBatches": len(self._parked),
            "families": len(self._families),
            "familyBindings": len(self._family_lane),
            "maxBatchReplicas": self.max_batch_replicas,
            "retryAfterS": self.retry_after_s(),
            "deviceGroups": self.device_groups,
            "nodeParallel": self.node_parallel,
            "horizonQuantumMs": self.horizon_quantum_ms,
            "lanes": [lane.describe() for lane in self._lanes],
            "waveWidthMax": self.metrics.wave_width_max,
        }

    def add_prometheus(self, p) -> None:
        from ..runtime.errors import taxonomy_counters
        from ..runtime.locks import lock_trace_status

        self.metrics.add_prometheus(p, self.queue)
        self.slo.add_prometheus(p)
        lt = lock_trace_status()
        if lt["armed"]:
            for name, row in sorted(lt["perLock"].items()):
                p.add(
                    "runtime_lock_wait_seconds", row["waitSecondsTotal"],
                    "cumulative seconds spent waiting to acquire each "
                    "registered lock (WITT_LOCK_TRACE only)",
                    "counter", {"lock": name},
                )
            p.add(
                "runtime_lock_order_violations_total",
                lt["violationCount"],
                "distinct lock-order violations observed by TracedLock",
                "counter",
            )
        p.add(
            "serve_draining",
            1 if self._draining.is_set() else 0,
            "1 while the scheduler is in graceful drain",
            "gauge",
        )
        for kind, n in sorted(taxonomy_counters().items()):
            p.add(
                "runtime_error_kind_total", n,
                "classified failures by error-taxonomy kind",
                "counter", {"kind": kind},
            )

"""The batching scheduler: compatible jobs share one compiled program.

Grouping discipline
-------------------
Two jobs may ride the same ``run_ms_batched`` dispatch iff they resolve
to the same **scenario family**: protocol name + every traced param
(anything not in the serve registry's ``state_only`` set) + simulation
horizon + execution mode (direct vs chunk schedule).  That pre-key is
computed at admission from the spec alone; when the family is first
built, the full static digest is extended with
``runtime.supervisor.stable_run_key`` over the engine + template leaf
signature — the same digest discipline the durable executor stamps into
checkpoints — so "compatible" is defined by what actually shapes the
trace, not by what the client claimed.  Everything else a job carries —
seed, FaultPlan, state-only params — is per-replica DATA.

Fixed-compile guarantee
-----------------------
Every dispatch is padded to a fixed replica capacity
(``max_batch_replicas``; padding rows are template copies whose results
are discarded), so every batch of a family presents the identical input
leaf signature to the run cache (parallel.replica_shard): ONE compile
per (family, horizon) however the workload arrives.  The run cache's
monotonic hit/miss/compile counters make the claim measurable — the
loadgen asserts it.

Families hold ONE engine object each on purpose: ``net.cache_key()``
includes ``id(protocol)``/``id(latency)``, so rebuilding the engine per
job would defeat the cache even with identical params (simlint SL801
pins this contract).

Preemption
----------
A job with ``chunkMs`` set runs through ``runtime.Supervisor`` in
slices of ``slice_chunks`` device calls, checkpointing every chunk via
``engine/checkpoint.CheckpointManager``.  Between slices the worker
checks the queue: queued work with strictly higher priority parks the
batch (its checkpoint is the park ticket) and runs first; the parked
batch later resumes from the checkpoint, bit-identical to an
uninterrupted run (the supervisor's replay contract).  The chunk
function is routed through the SAME run cache, so the chunked mode
costs one extra compile per family, not one per slice.
"""

from __future__ import annotations

import hashlib
import json
import os
import shutil
import tempfile
import threading
import time
from typing import Dict, List, Optional

from ..obs import (
    FlightRecorder,
    TraceContext,
    batch_attribution,
    get_recorder,
    mint_context,
)
from .jobs import (
    Job,
    JobQueue,
    JobSpec,
    JobState,
    QueueFullError,
    serve_protocol,
)
from .metrics import ServeMetrics


def _job_ctx(job: Job) -> TraceContext:
    """The admission-minted identity of a job as a TraceContext."""
    return TraceContext(
        run_id=job.run_id,
        job_id=job.id,
        tenant_id=job.spec.tenant if job.spec is not None else None,
    )


def _leaf_signature(state) -> tuple:
    """(path, shape, dtype) per leaf — rows packed together must agree
    exactly or the stacked program would differ from the family's."""
    import jax

    sig = []
    for path, leaf in jax.tree_util.tree_flatten_with_path(state)[0]:
        sig.append(
            (
                str(path),
                tuple(getattr(leaf, "shape", ())),
                str(getattr(leaf, "dtype", type(leaf).__name__)),
            )
        )
    return tuple(sig)


def state_digest(state) -> str:
    """Bitwise identity of a state pytree (side-cars included): blake2b
    over every leaf's path, dtype, shape, and raw bytes.  Two runs are
    'the same result' iff these match — the multi-tenant correctness
    contract (batched row == singleton run) is checked on this."""
    import jax
    import numpy as np

    h = hashlib.blake2b(digest_size=16)
    for path, leaf in jax.tree_util.tree_flatten_with_path(state)[0]:
        arr = np.asarray(leaf)
        h.update(str(path).encode())
        h.update(str(arr.dtype).encode())
        h.update(str(arr.shape).encode())
        h.update(arr.tobytes())
    return h.hexdigest()


class ScenarioFamily:
    """One compatibility class: a single engine object + per-params
    single-replica templates, all sharing one traced program."""

    def __init__(self, key, digest, net, entry, tele_cfg, sim_ms, chunk_ms,
                 base_params_key, base_template):
        self.key = key  # admission-time pre-key
        self.digest = digest  # full static digest (stable_run_key suffix)
        self.net = net
        self.entry = entry
        self.tele_cfg = tele_cfg
        self.sim_ms = sim_ms
        self.chunk_ms = chunk_ms
        self.templates: Dict[str, object] = {base_params_key: base_template}
        self.signature = _leaf_signature(base_template)


class _ParkedBatch:
    """A chunked batch between slices: the Supervisor (whose checkpoint
    directory is the resume ticket) plus the jobs riding it."""

    def __init__(self, batch_id, family, jobs, supervisor, ckpt_dir,
                 priority, capacity):
        self.batch_id = batch_id
        self.family = family
        self.jobs = jobs
        self.supervisor = supervisor
        self.ckpt_dir = ckpt_dir
        self.priority = priority
        self.capacity = capacity
        self.preempted = False
        self.started = time.monotonic()


class BatchScheduler:
    """Queue consumer: groups, packs, dispatches, streams progress.

    One worker thread serializes all device work (the engine is
    replica-parallel, not request-parallel); HTTP handlers only touch
    the queue and job records.  ``auto_start=False`` leaves the worker
    off so tests can drive ``drain_once()`` deterministically."""

    def __init__(
        self,
        queue: Optional[JobQueue] = None,
        metrics: Optional[ServeMetrics] = None,
        *,
        max_batch_replicas: int = 8,
        slice_chunks: int = 2,
        telemetry_snapshots: int = 32,
        checkpoint_root: Optional[str] = None,
        auto_start: bool = True,
        recorder: Optional[FlightRecorder] = None,
    ):
        if max_batch_replicas < 1:
            raise ValueError(
                f"max_batch_replicas must be >= 1, got {max_batch_replicas}"
            )
        self.queue = queue or JobQueue()
        self.metrics = metrics or ServeMetrics()
        self.max_batch_replicas = max_batch_replicas
        self.slice_chunks = max(1, slice_chunks)
        self.telemetry_snapshots = telemetry_snapshots
        self.checkpoint_root = checkpoint_root or os.path.join(
            tempfile.gettempdir(), f"witt_serve_ckpt_{os.getpid()}"
        )
        self.auto_start = auto_start
        # flight recorder: admission / packing / dispatch decisions land
        # here alongside the supervisor's chunk events (one ring per
        # process by default, see obs.get_recorder)
        self.recorder = recorder if recorder is not None else get_recorder()
        self._families: Dict[str, ScenarioFamily] = {}
        self._fam_lock = threading.Lock()
        self._parked: List[_ParkedBatch] = []
        self._batch_seq = 0
        self._ema_batch_s = 1.0
        self._worker: Optional[threading.Thread] = None
        self._worker_lock = threading.Lock()
        self._stop = threading.Event()

    # -- admission -----------------------------------------------------

    def pre_key(self, spec: JobSpec) -> str:
        """Compatibility pre-key from the spec alone (no engine build):
        protocol + traced params + horizon + chunk schedule + telemetry
        geometry.  Jobs sharing it are CANDIDATES for one batch; the
        family build extends it with the template leaf signature."""
        entry = serve_protocol(spec.protocol)
        traced = {
            k: spec.params[k]
            for k in sorted(spec.params)
            if k not in entry.state_only
        }
        payload = json.dumps(
            {
                "protocol": spec.protocol,
                "traced": traced,
                "sim_ms": spec.sim_ms,
                "chunk_ms": spec.chunk_ms,
                "snapshots": self.telemetry_snapshots,
            },
            sort_keys=True,
            default=str,
        )
        return "fam-" + hashlib.blake2b(
            payload.encode(), digest_size=8
        ).hexdigest()

    def retry_after_s(self) -> int:
        """Seconds until queued work likely drains one batch slot, from
        the EMA batch wall time (RFC 9110: >= 1)."""
        batches_ahead = self.queue.depth() // self.max_batch_replicas + 1
        return max(1, int(batches_ahead * self._ema_batch_s + 0.5))

    def submit(self, spec_dict: dict) -> Job:
        """Parse, validate, and enqueue one job (raises ValueError /
        KeyError on a malformed spec, QueueFullError on backpressure).
        This is where the job's run_id is minted (Job.__post_init__) —
        the first flight-recorder event of the run is its admission."""
        spec = JobSpec.from_dict(spec_dict)
        job = Job(spec=spec, compat=self.pre_key(spec),
                  priority=spec.priority)
        try:
            self.queue.submit(job, retry_after_s=self.retry_after_s())
        except QueueFullError as e:
            self.recorder.record(
                "admission-rejected", ctx=_job_ctx(job),
                protocol=spec.protocol, depth=e.depth,
                retry_after_s=e.retry_after_s,
            )
            raise
        self.recorder.record(
            "admission", ctx=_job_ctx(job),
            protocol=spec.protocol, compat=job.compat,
            sim_ms=spec.sim_ms, chunk_ms=spec.chunk_ms or None,
            priority=spec.priority or None,
            queue_depth=self.queue.depth(),
        )
        self.metrics.observe_submit()
        if self.auto_start:
            self.start()
        return job

    def submit_legacy(self, thunk, priority: int = 0) -> Job:
        """Queue an opaque host-side thunk (the rerouted /w/sweep): it
        occupies one worker turn and is never packed with batch jobs."""
        job = Job(spec=None, compat="", kind="legacy", thunk=thunk,
                  priority=priority)
        job.compat = f"legacy-{job.id}"
        self.queue.submit(job, retry_after_s=self.retry_after_s())
        self.metrics.observe_submit()
        if self.auto_start:
            self.start()
        return job

    def cancel(self, job_id: str) -> Job:
        job, cancelled_now = self.queue.cancel(job_id)
        if cancelled_now:
            self.metrics.observe_job(job)
            self.queue.retire(job)
        return job

    # -- families ------------------------------------------------------

    @staticmethod
    def _params_key(params: dict) -> str:
        return json.dumps(params, sort_keys=True, default=str)

    def family_for(self, spec: JobSpec) -> ScenarioFamily:
        key = self.pre_key(spec)
        with self._fam_lock:
            fam = self._families.get(key)
            if fam is not None:
                return fam
            from ..runtime.supervisor import stable_run_key
            from ..telemetry import TelemetryConfig

            snaps = self.telemetry_snapshots
            tele_cfg = TelemetryConfig(
                snapshots=snaps,
                snapshot_every_ms=max(1, spec.sim_ms // max(1, snaps)),
            )
            entry = serve_protocol(spec.protocol)
            net, state = entry.build(spec.params, tele_cfg)
            # faults are ALWAYS armed: a fault-free job is the neutral
            # schedule (bit-identical by the SL406 contract), so one
            # program serves faulted and clean rows alike
            net, state = net.with_faults(state)
            n_chunks = (
                spec.sim_ms // spec.chunk_ms if spec.chunk_ms else 1
            )
            digest = key + "/" + stable_run_key(
                net, state, n_chunks, spec.chunk_ms or spec.sim_ms
            )
            fam = ScenarioFamily(
                key, digest, net, entry, tele_cfg, spec.sim_ms,
                spec.chunk_ms, self._params_key(spec.params), state,
            )
            self._families[key] = fam
            return fam

    def _template_for(self, fam: ScenarioFamily, spec: JobSpec):
        pk = self._params_key(spec.params)
        st = fam.templates.get(pk)
        if st is not None:
            return st
        # params differ only in state-only fields (same pre-key): build
        # the layout with a throwaway engine, arm side-cars through the
        # FAMILY net so the signature discipline is identical, and keep
        # only the state
        _, st = fam.entry.build(spec.params, fam.tele_cfg)
        _, st = fam.net.with_faults(st)
        if _leaf_signature(st) != fam.signature:
            raise ValueError(
                f"params {spec.params} change the traced program despite "
                f"matching family {fam.key} — state-only contract "
                "violation (simlint SL801)"
            )
        fam.templates[pk] = st
        return st

    def _row(self, fam: ScenarioFamily, spec: JobSpec):
        st = self._template_for(fam, spec)
        # seed is per-replica data; `*0 +` keeps the leaf dtype exact
        return st._replace(seed=st.seed * 0 + spec.seed)

    def _pack(self, fam: ScenarioFamily, jobs: List[Job]):
        """Stack job rows + padding to the fixed replica capacity and
        attach the per-row fault schedules.  The padding rows are the
        base template (results discarded): every batch of a family has
        the identical leaf signature -> one compile, ever."""
        from ..engine import stack_states
        from ..faults.plan import lower_plans

        base = next(iter(fam.templates.values()))
        rows = [self._row(fam, j.spec) for j in jobs]
        rows += [base] * (self.max_batch_replicas - len(rows))
        stacked = stack_states(rows)
        plans = [j.spec.plan for j in jobs]
        plans += [None] * (self.max_batch_replicas - len(plans))
        fs = lower_plans(
            plans, fam.net.n_nodes, fam.net.protocol.n_msg_types()
        )
        return stacked._replace(faults=fs)

    # -- results -------------------------------------------------------

    def _row_result(self, fam: ScenarioFamily, row) -> dict:
        import numpy as np

        from ..telemetry.export import counters, progress_series

        return {
            "digest": state_digest(row),
            "time": int(np.asarray(row.time)),
            "counters": counters(fam.net, row),
            "progress": progress_series(row),
        }

    def run_singleton(self, spec_dict: dict) -> dict:
        """Reference result for one spec: a 1-row stack through the
        engine directly (no packing, no run cache).  The multi-tenant
        contract is that every batched job's result digest equals this
        — rows of a vmapped run are lane-independent.  A chunked spec
        replays the SAME chunk schedule: the sim state is schedule-
        independent, but the telemetry loop census (jumps cannot cross
        a chunk boundary) is part of the digested side-car."""
        import jax

        from ..engine import stack_states
        from ..faults.plan import lower_plans

        spec = JobSpec.from_dict(spec_dict)
        fam = self.family_for(spec)
        row = self._row(fam, spec)
        stacked = stack_states([row])
        fs = lower_plans(
            [spec.plan], fam.net.n_nodes, fam.net.protocol.n_msg_types()
        )
        out = stacked._replace(faults=fs)
        step = spec.chunk_ms or spec.sim_ms
        for _ in range(spec.sim_ms // step):
            out = fam.net.run_ms_batched(out, step)
        single = jax.tree_util.tree_map(lambda a: a[0], out)
        return self._row_result(fam, single)

    # -- planning (also the simlint SL801 surface) ---------------------

    def plan_batches(self) -> List[dict]:
        """Group the pending queue into dispatch plans WITHOUT removing
        or running anything: highest-priority-first, FIFO within a
        family, capped at the replica capacity.  Every plan's jobs share
        one compat key by construction — the property simlint's
        scheduler-contract pass verifies against the full static
        digests."""
        remaining = sorted(
            self.queue.pending_snapshot(),
            key=lambda j: (-j.priority, j.seq),
        )
        plans = []
        while remaining:
            head = remaining[0]
            same = [j for j in remaining if j.compat == head.compat]
            take = same[: self.max_batch_replicas]
            taken = set(id(j) for j in take)
            remaining = [j for j in remaining if id(j) not in taken]
            plans.append(
                {
                    "compat": head.compat,
                    "priority": head.priority,
                    "jobs": [j.id for j in take],
                    "kind": head.kind,
                }
            )
        return plans

    # -- dispatch ------------------------------------------------------

    def drain_once(self) -> bool:
        """One scheduling decision: resume the best parked batch or
        dispatch the best pending group.  Returns False when idle.
        Deterministic entry point for tests; the worker loop just calls
        this."""
        parked = max(
            self._parked, key=lambda b: (b.priority, -b.started),
            default=None,
        )
        best = self.queue.best_pending()
        if parked is not None and (
            best is None or best.priority <= parked.priority
        ):
            return self._continue_parked(parked)
        if best is None:
            return False
        if parked is not None and best.priority > parked.priority:
            if not parked.preempted:
                parked.preempted = True
                self.metrics.observe_preemption()
        jobs = self.queue.take_batch(
            best.compat,
            1 if best.kind == "legacy" else self.max_batch_replicas,
        )
        if not jobs:
            return False
        if best.kind == "legacy":
            self._run_legacy(jobs[0])
            return True
        self._dispatch(jobs)
        return True

    def _finish_job(self, job: Job, state: JobState, **kw) -> None:
        job.finish(state, **kw)
        self.metrics.observe_job(job)
        self.queue.retire(job)

    def _run_legacy(self, job: Job) -> None:
        job.state = JobState.RUNNING
        job.started_at = time.monotonic()
        if job.cancel_requested:
            self._finish_job(job, JobState.CANCELLED)
            return
        try:
            result = job.thunk()
        except BaseException as e:  # noqa: BLE001 — surfaced to waiter
            self._finish_job(
                job, JobState.FAILED,
                error=f"{type(e).__name__}: {e}", exc=e,
            )
            return
        self._finish_job(job, JobState.DONE, result=result)

    def _dispatch(self, jobs: List[Job]) -> None:
        live = []
        for j in jobs:
            if j.cancel_requested:
                self._finish_job(j, JobState.CANCELLED)
            else:
                live.append(j)
        if not live:
            return
        # scheduler contract (simlint SL801): one batch, one digest
        compat = {j.compat for j in live}
        if len(compat) != 1:
            raise RuntimeError(
                f"batch mixes compatibility keys {sorted(compat)}"
            )
        try:
            fam = self.family_for(live[0].spec)
            stacked = self._pack(fam, live)
        except BaseException as e:  # noqa: BLE001 — build/pack failure
            for j in live:
                self._finish_job(
                    j, JobState.FAILED,
                    error=f"{type(e).__name__}: {e}", exc=e,
                )
            return
        self._batch_seq += 1
        batch_id = f"batch-{self._batch_seq:05d}"
        now = time.monotonic()
        for j in live:
            j.state = JobState.RUNNING
            j.started_at = now
            j.batch_id = batch_id
        # the batch gets its own run identity (it IS the device run);
        # the pack event records the join batch run_id <-> member job
        # run_ids, so obs_query can walk from any job to its chunks
        batch_ctx = mint_context("batch")
        self.recorder.record(
            "pack", ctx=batch_ctx, batch_id=batch_id,
            compat=live[0].compat, family_digest=fam.digest,
            mode="chunked" if fam.chunk_ms else "direct",
            members=[
                {
                    "job_id": j.id,
                    "run_id": j.run_id,
                    "tenant": j.spec.tenant,
                    "replica": i,
                }
                for i, j in enumerate(live)
            ],
            live_rows=len(live),
            padding_rows=self.max_batch_replicas - len(live),
            capacity=self.max_batch_replicas,
        )
        if fam.chunk_ms:
            self._start_chunked(batch_id, fam, live, stacked, batch_ctx)
        else:
            self._dispatch_direct(batch_id, fam, live, stacked, batch_ctx)

    def _dispatch_direct(self, batch_id, fam, jobs, stacked, ctx=None) -> None:
        from ..parallel.replica_shard import sharded_run_stats

        t0 = time.monotonic()
        try:
            out, _stats = sharded_run_stats(fam.net, stacked, fam.sim_ms)
            self._finalize(fam, jobs, out)
        except BaseException as e:  # noqa: BLE001 — device failure
            self.recorder.record(
                "batch-failed", ctx=ctx, batch_id=batch_id,
                error=f"{type(e).__name__}: {e}"[:500],
            )
            for j in jobs:
                self._finish_job(
                    j, JobState.FAILED,
                    error=f"{type(e).__name__}: {e}", exc=e,
                )
            return
        finally:
            dt = time.monotonic() - t0
            self._ema_batch_s = 0.5 * self._ema_batch_s + 0.5 * dt
            self.metrics.observe_batch(
                len(jobs), self.max_batch_replicas, dt
            )

    def _start_chunked(self, batch_id, fam, jobs, stacked, ctx=None) -> None:
        from ..parallel.replica_shard import _run_and_reduce
        from ..runtime.supervisor import Supervisor, stable_run_key

        n_chunks = fam.sim_ms // fam.chunk_ms
        ckpt_dir = os.path.join(self.checkpoint_root, batch_id)
        # the chunk function goes through the run cache too: chunked
        # mode costs ONE extra compile per family, not one per slice
        cached = _run_and_reduce(fam.net, fam.chunk_ms)
        sup = Supervisor(
            lambda s: cached(s)[0],
            stacked,
            n_chunks=n_chunks,
            chunk_ms=fam.chunk_ms,
            checkpoint_dir=ckpt_dir,
            checkpoint_every=1,
            run_key=stable_run_key(fam.net, stacked, n_chunks, fam.chunk_ms),
            max_chunks_this_run=self.slice_chunks,
            ctx=ctx,
            recorder=self.recorder,
            run_meta={
                "batch_id": batch_id,
                "members": [
                    {"job_id": j.id, "run_id": j.run_id,
                     "tenant": j.spec.tenant}
                    for j in jobs
                ],
            },
        )
        parked = _ParkedBatch(
            batch_id, fam, jobs, sup, ckpt_dir,
            max(j.priority for j in jobs), self.max_batch_replicas,
        )
        self._parked.append(parked)
        self._continue_parked(parked)

    def _continue_parked(self, parked: _ParkedBatch) -> bool:
        if parked.preempted:
            parked.preempted = False
            self.metrics.observe_resume()
        if all(j.cancel_requested for j in parked.jobs):
            for j in parked.jobs:
                self._finish_job(j, JobState.CANCELLED)
            self._drop_parked(parked)
            return True
        t0 = time.monotonic()
        try:
            report = parked.supervisor.run()
        except BaseException as e:  # noqa: BLE001 — supervised failure
            # the supervisor already recorded + dumped its black box;
            # this event marks the batch-level consequence
            self.recorder.record(
                "batch-failed", ctx=parked.supervisor.ctx,
                batch_id=parked.batch_id,
                error=f"{type(e).__name__}: {e}"[:500],
            )
            for j in parked.jobs:
                self._finish_job(
                    j, JobState.FAILED,
                    error=f"{type(e).__name__}: {e}", exc=e,
                )
            self._drop_parked(parked)
            return True
        dt = time.monotonic() - t0
        self._ema_batch_s = 0.5 * self._ema_batch_s + 0.5 * dt
        self.metrics.observe_batch(len(parked.jobs), parked.capacity, dt)
        self._stream_progress(parked, report.state)
        if report.ok:
            self._finalize(parked.family, parked.jobs, report.state)
            self._drop_parked(parked)
        # ok=False: a controlled partial stop — the batch stays parked
        # (checkpoint on disk) and the next drain_once decides whether
        # it continues or yields to higher-priority work
        return True

    def _stream_progress(self, parked: _ParkedBatch, stacked) -> None:
        from ..telemetry.export import progress_series

        # live per-tenant attribution at the slice boundary: /w/jobs
        # shows who is consuming the batch while it runs, not only at
        # the end
        attrib = self._attribution(parked.family, parked.jobs, stacked)
        for i, job in enumerate(parked.jobs):
            if job.state is not JobState.RUNNING:
                continue
            if attrib is not None:
                job.attribution = self._job_attribution(attrib, job)
            series = progress_series(stacked, replica=i)
            if series:
                job.progress = series
                self.metrics.observe_ttfr(job)

    def _drop_parked(self, parked: _ParkedBatch) -> None:
        if parked in self._parked:
            self._parked.remove(parked)
        shutil.rmtree(parked.ckpt_dir, ignore_errors=True)

    # -- attribution ----------------------------------------------------

    def _attribution(self, fam, jobs: List[Job], out) -> Optional[dict]:
        """Per-tenant counter slice of a packed batch (obs module);
        read-only over the final state, never affects the result
        digests."""
        try:
            return batch_attribution(
                fam.net,
                out,
                [
                    {"job_id": j.id, "run_id": j.run_id,
                     "tenant": j.spec.tenant}
                    for j in jobs
                ],
                self.max_batch_replicas,
            )
        except (TypeError, ValueError, AttributeError):
            return None  # attribution must never fail a batch

    @staticmethod
    def _job_attribution(attrib: dict, job: Job) -> dict:
        """The one-job status view: this job's row slice, its tenant's
        aggregate, and the batch totals they reconcile against."""
        return {
            "job": attrib["jobs"].get(job.id),
            "tenant": attrib["tenants"].get(job.spec.tenant),
            "batch": attrib["batch"],
        }

    def _finalize(self, fam: ScenarioFamily, jobs: List[Job], out) -> None:
        import jax

        attrib = self._attribution(fam, jobs, out)
        for i, job in enumerate(jobs):
            if job.cancel_requested:
                self._finish_job(job, JobState.CANCELLED)
                continue
            row = jax.tree_util.tree_map(lambda a, i=i: a[i], out)
            result = self._row_result(fam, row)
            job.progress = result["progress"]
            if attrib is not None:
                job.attribution = self._job_attribution(attrib, job)
                result["attribution"] = job.attribution
                self.metrics.observe_tenant(
                    job.spec.tenant, attrib["jobs"].get(job.id)
                )
            self._finish_job(job, JobState.DONE, result=result)

    # -- worker --------------------------------------------------------

    def start(self) -> None:
        # auto_start means every submit calls this: a burst of first
        # requests races the is_alive check and, unguarded, each spawns
        # its own (identically named) worker — concurrent workers then
        # duplicate batch compiles.  ONE worker is the design.
        with self._worker_lock:
            if self._worker is not None and self._worker.is_alive():
                return
            self._stop.clear()
            self._worker = threading.Thread(
                target=self._loop, daemon=True, name="witt-serve-worker"
            )
            self._worker.start()

    def stop(self, timeout: float = 5.0) -> None:
        self._stop.set()
        self.queue.notify()
        with self._worker_lock:
            worker = self._worker
            self._worker = None
        if worker is not None:
            worker.join(timeout)

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                if not self.drain_once():
                    self.queue.wait_for_work(timeout=0.2)
            except Exception:  # noqa: BLE001 — worker must not die
                # per-job failures are reported on the jobs themselves;
                # anything reaching here is a scheduler bug — park for a
                # beat instead of spinning
                time.sleep(0.1)

    def busy(self) -> bool:
        return bool(self._parked) or self.queue.depth() > 0

    def wait_idle(self, timeout: float = 60.0) -> bool:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if not self.busy():
                return True
            time.sleep(0.02)
        return not self.busy()

    # -- exposition ----------------------------------------------------

    def status(self) -> dict:
        return {
            "queueDepth": self.queue.depth(),
            "queueCapacity": self.queue.max_depth,
            "parkedBatches": len(self._parked),
            "families": len(self._families),
            "maxBatchReplicas": self.max_batch_replicas,
            "retryAfterS": self.retry_after_s(),
        }

    def add_prometheus(self, p) -> None:
        self.metrics.add_prometheus(p, self.queue)

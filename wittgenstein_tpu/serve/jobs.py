"""Job model + queue for the multi-tenant serving layer.

The reference serves one simulation per process behind one global run
lock (ws/WServer.java); a second client gets 503.  The batched engine
inverts that economics: heterogeneous per-replica scenarios — seeds,
FaultPlans, sweepable state-only params — are DATA on the replica axis
of ONE compiled program, so the serving layer's job is admission +
grouping, not time-slicing.  This module owns the host-side half:

  * ``JobSpec``: a client request parsed/validated once at admission —
    protocol name, full params, seed, optional FaultPlan (built from a
    JSON op list by ``plan_from_spec``), sim horizon, execution mode
    (direct vs chunked/preemptible) and priority;
  * ``Job``: the queued unit with a typed lifecycle
    (QUEUED -> RUNNING -> DONE | FAILED | CANCELLED | QUARANTINED,
    the last being the 4xx-style verdict of batch salvage — the spec
    itself is the fault), timestamps for
    the SLO quantiles, a threading.Event for blocking waiters, and a
    cancel flag honored at batch boundaries;
  * ``JobQueue``: a bounded registry + pending list.  Admission control
    is the bound: a full queue raises ``QueueFullError`` carrying a
    Retry-After estimate instead of wedging an HTTP worker — the
    backpressure contract the server maps to 429/503;
  * the serve-side protocol registry (``SERVE_PROTOCOLS``): which
    factories the scheduler may build engine families from, and which
    param fields are per-replica DATA (state-only — safe to vary
    inside one compiled program) versus traced shape/branch params
    (anything else — a different compiled program, scheduler.compat
    splits the batch).

Scheduling itself — compatibility digests, replica packing, dispatch —
lives in serve/scheduler.py.
"""

from __future__ import annotations

import dataclasses
import enum
import itertools
import threading
import time
from typing import Callable, Dict, List, Optional


class JobState(str, enum.Enum):
    QUEUED = "queued"
    RUNNING = "running"
    DONE = "done"
    FAILED = "failed"
    CANCELLED = "cancelled"
    # terminal 4xx-style status: batch salvage proved this job's row is
    # what failed the batch (the batch succeeds without it), so the
    # fault is the SPEC's, not the fleet's — resubmitting unchanged
    # reproduces it.  Distinct from FAILED (a 5xx: the fleet broke).
    QUARANTINED = "quarantined"


#: terminal states: the job's Event is set and its record is immutable
TERMINAL = frozenset({
    JobState.DONE,
    JobState.FAILED,
    JobState.CANCELLED,
    JobState.QUARANTINED,
})


class QueueFullError(Exception):
    """Admission refused: the pending queue is at capacity.  Carries the
    scheduler's Retry-After estimate (seconds) for the HTTP layer."""

    def __init__(self, depth: int, retry_after_s: int):
        super().__init__(
            f"job queue full ({depth} pending); retry in ~{retry_after_s}s"
        )
        self.depth = depth
        self.retry_after_s = retry_after_s


class DrainingError(Exception):
    """Admission refused: the scheduler is in graceful drain (admin
    surface).  Carries the Retry-After hint for the HTTP 503."""

    def __init__(self, retry_after_s: int):
        super().__init__(
            f"scheduler is draining; retry in ~{retry_after_s}s"
        )
        self.retry_after_s = retry_after_s


class UnknownJobError(KeyError):
    pass


# ---------------------------------------------------------------------------
# serve-side protocol registry


@dataclasses.dataclass(frozen=True)
class ServeProtocol:
    """One protocol family the scheduler may serve.

    ``build(params, telemetry)`` -> (net, single-replica state) with the
    telemetry side-car armed at construction.  ``state_only`` names the
    param fields that are per-replica data (distinct values can share
    one compiled program — the same set sweep.py's config grouping
    uses); every OTHER param is assumed traced and splits the
    compatibility key."""

    name: str
    build: Callable
    state_only: frozenset = frozenset()


def _build_pingpong(params: dict, telemetry):
    from ..protocols.pingpong_batched import make_pingpong

    return make_pingpong(
        node_ct=int(params.get("node_ct", 64)),
        node_builder_name=params.get("node_builder_name"),
        network_latency_name=params.get("network_latency_name"),
        capacity=params.get("capacity"),
        wheel_rows=params.get("wheel_rows"),
        telemetry=telemetry,
    )


def _build_p2pflood(params: dict, telemetry):
    from ..protocols.p2pflood import P2PFloodParameters
    from ..protocols.p2pflood_batched import make_p2pflood

    p = P2PFloodParameters(**params)
    return make_p2pflood(p, telemetry=telemetry)


def _build_handel(params: dict, telemetry):
    from ..protocols.handel import HandelParameters
    from ..protocols.handel_batched import make_handel

    p = HandelParameters(**params)
    return make_handel(p, telemetry=telemetry)


def _handel_state_only() -> frozenset:
    # single source of truth: the sweep runner's grouping fields
    from ..scenarios.sweep import _STATE_ONLY_FIELDS

    return _STATE_ONLY_FIELDS


SERVE_PROTOCOLS: Dict[str, ServeProtocol] = {
    "PingPong": ServeProtocol("PingPong", _build_pingpong),
    "P2PFlood": ServeProtocol(
        "P2PFlood",
        _build_p2pflood,
        # dead_node_count maps to init-state down flags (per-replica
        # data would need stacked init states; the factory handles it
        # per build, so it is state-only for grouping purposes)
        frozenset({"dead_node_count"}),
    ),
    "Handel": ServeProtocol("Handel", _build_handel),
}


def serve_protocol(name: str) -> ServeProtocol:
    entry = SERVE_PROTOCOLS.get(name)
    if entry is None:
        raise KeyError(
            f"unknown serve protocol {name!r} "
            f"(known: {sorted(SERVE_PROTOCOLS)})"
        )
    if entry.name == "Handel" and not entry.state_only:
        entry = dataclasses.replace(entry, state_only=_handel_state_only())
        SERVE_PROTOCOLS[name] = entry
    return entry


# ---------------------------------------------------------------------------
# horizon sharding


def chunk_schedule(
    sim_ms: int, chunk_ms: int = 0, quantum_ms: int = 0
) -> List[int]:
    """The exact sequence of run_ms steps a job executes — ONE function
    so the batched path and the singleton reference replay the same
    boundaries (the telemetry loop census is chunk-schedule-dependent,
    so bit-identity requires agreeing on this list).

    Explicit ``chunk_ms`` wins (admission validated divisibility).
    Otherwise a scheduler-level ``quantum_ms`` splits any longer horizon
    into fixed quantum units plus one remainder step, so mixed-simMs
    tenants share one chunked family instead of fragmenting into
    per-horizon compiled programs.  Horizons >= the quantum are
    quantized (sim_ms == quantum is ONE quantum unit — it rides the
    shared chunked family, not a private direct one); shorter horizons
    stay direct (one step)."""
    if chunk_ms:
        return [chunk_ms] * (sim_ms // chunk_ms)
    if quantum_ms and sim_ms >= quantum_ms:
        full, rem = divmod(sim_ms, quantum_ms)
        return [quantum_ms] * full + ([rem] if rem else [])
    return [sim_ms]


# ---------------------------------------------------------------------------
# fault-plan parsing


_PLAN_OPS = ("crash", "partition", "drop", "inflate", "silence", "delay")


def plan_from_spec(ops: Optional[List[dict]], label: str = "job"):
    """Build a FaultPlan from a JSON op list, e.g.::

        [{"op": "crash", "nodes": [1, 2], "at": 100, "recover": 400},
         {"op": "drop", "per_mille": 200, "start": 50}]

    None / empty -> None (the neutral schedule: a fault-free row of a
    fault-enabled program, bit-identical by the SL406 contract).  Ops
    map 1:1 onto faults.FaultPlan builder methods; unknown ops or
    malformed windows raise ValueError at ADMISSION, not at dispatch.
    """
    if not ops:
        return None
    from ..faults.plan import FaultPlan

    plan = FaultPlan(label)
    for op in ops:
        kind = op.get("op")
        if kind not in _PLAN_OPS:
            raise ValueError(
                f"unknown fault op {kind!r} (known: {_PLAN_OPS})"
            )
        kw = {k: v for k, v in op.items() if k != "op"}
        getattr(plan, kind)(**kw)
    return plan


# ---------------------------------------------------------------------------
# job model


@dataclasses.dataclass
class JobSpec:
    """One client request, validated at admission.

    chunk_ms > 0 selects the chunked (checkpointed, preemptible)
    execution path; 0 runs the whole horizon in one device call."""

    protocol: str
    params: dict
    seed: int = 0
    plan: object = None  # FaultPlan | None
    plan_ops: Optional[List[dict]] = None  # original JSON, for echo
    sim_ms: int = 1000
    chunk_ms: int = 0
    priority: int = 0
    # attribution identity only — never part of the compatibility key,
    # so tenants pack together freely (isolation is accounting, not
    # placement)
    tenant: str = "default"

    @classmethod
    def from_dict(cls, spec: dict) -> "JobSpec":
        protocol = spec.get("protocol")
        if not protocol:
            raise ValueError("job spec needs a 'protocol'")
        serve_protocol(protocol)  # admission-time existence check
        sim_ms = int(spec.get("simMs", spec.get("sim_ms", 1000)))
        if sim_ms < 1:
            raise ValueError(f"simMs must be >= 1, got {sim_ms}")
        chunk_ms = int(spec.get("chunkMs", spec.get("chunk_ms", 0)))
        if chunk_ms < 0:
            raise ValueError(f"chunkMs must be >= 0, got {chunk_ms}")
        if chunk_ms and sim_ms % chunk_ms != 0:
            raise ValueError(
                f"simMs={sim_ms} must be a multiple of chunkMs={chunk_ms}"
            )
        ops = spec.get("faults")
        tenant = str(spec.get("tenant", spec.get("tenantId", "default")))
        if not tenant:
            raise ValueError("tenant must be a non-empty string")
        return cls(
            protocol=protocol,
            params=dict(spec.get("params", {})),
            seed=int(spec.get("seed", 0)),
            plan=plan_from_spec(ops),
            plan_ops=ops,
            sim_ms=sim_ms,
            chunk_ms=chunk_ms,
            priority=int(spec.get("priority", 0)),
            tenant=tenant,
        )


_JOB_SEQ = itertools.count(1)


@dataclasses.dataclass
class Job:
    """A queued unit of work.  ``kind`` is "batch" (packable onto the
    replica axis) or "legacy" (an opaque thunk — the rerouted /w/sweep
    path — never packed with anything)."""

    spec: Optional[JobSpec]
    compat: str  # pre-dispatch compatibility key (scheduler.pre_key)
    kind: str = "batch"
    thunk: Optional[Callable] = None  # legacy jobs only
    id: str = ""
    seq: int = 0
    state: JobState = JobState.QUEUED
    priority: int = 0
    submitted_at: float = 0.0
    started_at: Optional[float] = None
    first_result_at: Optional[float] = None
    finished_at: Optional[float] = None
    progress: List[dict] = dataclasses.field(default_factory=list)
    result: Optional[dict] = None
    error: Optional[str] = None
    # runtime.errors.classify kind of the failure ("poison_row",
    # "lane_failed", "fatal", ...) — the honest-status field /w/jobs
    # payloads surface so clients can tell "your spec is poison" (4xx)
    # from "the fleet broke" (5xx)
    error_kind: Optional[str] = None
    exc: Optional[BaseException] = None
    cancel_requested: bool = False
    batch_id: Optional[str] = None
    # obs spine: run_id is minted at ADMISSION (the earliest moment the
    # work exists) and joins this job's flight-recorder events, spans,
    # checkpoint manifests and metrics samples; attribution is the
    # per-tenant counter slice filled in by the scheduler at batch
    # boundaries (obs.batch_attribution)
    run_id: str = ""
    attribution: Optional[dict] = None
    done_event: threading.Event = dataclasses.field(
        default_factory=threading.Event
    )

    def __post_init__(self):
        if not self.id:
            self.seq = next(_JOB_SEQ)
            self.id = f"job-{self.seq:06d}"
        if not self.run_id:
            from ..obs import new_run_id

            self.run_id = new_run_id("job")

    def finish(self, state: JobState, *, result=None, error=None,
               error_kind=None, exc=None):
        self.state = state
        self.result = result
        self.error = error
        self.error_kind = error_kind
        self.exc = exc
        self.finished_at = time.monotonic()
        if self.first_result_at is None and state is JobState.DONE:
            self.first_result_at = self.finished_at
        self.done_event.set()

    def to_dict(self) -> dict:
        """Status payload (GET /w/jobs/{id}); results are served by the
        result endpoint so status stays small."""
        out = {
            "id": self.id,
            "runId": self.run_id,
            "state": self.state.value,
            "kind": self.kind,
            "priority": self.priority,
            "compat": self.compat,
            "batchId": self.batch_id,
            "progress": self.progress,
            "cancelRequested": self.cancel_requested,
        }
        if self.spec is not None:
            out["protocol"] = self.spec.protocol
            out["simMs"] = self.spec.sim_ms
            out["chunkMs"] = self.spec.chunk_ms
            out["seed"] = self.spec.seed
            out["tenant"] = self.spec.tenant
        if self.attribution is not None:
            out["attribution"] = self.attribution
        if self.error:
            out["error"] = self.error
        if self.error_kind:
            out["errorKind"] = self.error_kind
        return out


# ---------------------------------------------------------------------------
# queue


class JobQueue:
    """Bounded pending list + full job registry.

    The bound covers PENDING jobs only — completed records stay
    addressable for result pickup (bounded by ``keep_done``, FIFO
    pruned).  All mutation happens under one lock; ``wait_for_work``
    parks the scheduler worker on the condition variable."""

    def __init__(self, max_depth: int = 64, keep_done: int = 512):
        if max_depth < 1:
            raise ValueError(f"max_depth must be >= 1, got {max_depth}")
        self.max_depth = max_depth
        self.keep_done = keep_done
        self._lock = threading.Lock()
        self._work = threading.Condition(self._lock)
        self._pending: List[Job] = []
        self._jobs: Dict[str, Job] = {}
        self._done_order: List[str] = []
        self.rejected_total = 0

    # -- admission -----------------------------------------------------

    def submit(self, job: Job, retry_after_s: int = 1) -> Job:
        with self._lock:
            if len(self._pending) >= self.max_depth:
                self.rejected_total += 1
                raise QueueFullError(len(self._pending), retry_after_s)
            job.submitted_at = time.monotonic()
            self._pending.append(job)
            self._jobs[job.id] = job
            self._work.notify_all()
        return job

    # -- lookup --------------------------------------------------------

    def get(self, job_id: str) -> Job:
        with self._lock:
            job = self._jobs.get(job_id)
        if job is None:
            raise UnknownJobError(f"no such job {job_id!r}")
        return job

    def depth(self) -> int:
        with self._lock:
            return len(self._pending)

    def depth_for(self, compat: str) -> int:
        """Pending jobs of one compatibility family — the per-family
        Retry-After pacing reads this instead of the global depth, so a
        slow family's backlog doesn't inflate a fast family's hint."""
        with self._lock:
            return sum(1 for j in self._pending if j.compat == compat)

    def jobs(self) -> List[Job]:
        with self._lock:
            return list(self._jobs.values())

    # -- scheduler interface -------------------------------------------

    def pending_snapshot(self) -> List[Job]:
        """Read-only copy of the pending list (batch planning / simlint
        contract checks — nothing is removed)."""
        with self._lock:
            return list(self._pending)

    def best_pending(self) -> Optional[Job]:
        """Highest-priority, oldest pending job (no removal)."""
        with self._lock:
            if not self._pending:
                return None
            return max(self._pending, key=lambda j: (j.priority, -j.seq))

    def has_higher_priority(self, priority: int) -> bool:
        with self._lock:
            return any(j.priority > priority for j in self._pending)

    def take_batch(self, compat: str, max_n: int) -> List[Job]:
        """Remove and return up to ``max_n`` pending jobs sharing
        ``compat``, in FIFO order — the scheduler packs these onto one
        replica axis."""
        with self._lock:
            picked: List[Job] = []
            rest: List[Job] = []
            for j in self._pending:
                if j.compat == compat and len(picked) < max_n:
                    picked.append(j)
                else:
                    rest.append(j)
            self._pending = rest
            return picked

    def requeue(self, jobs: List[Job]) -> None:
        """Return jobs to the pending list (front, preserving seq order)
        — used when a dispatch is abandoned before running."""
        with self._lock:
            self._pending = sorted(
                jobs + self._pending, key=lambda j: j.seq
            )
            self._work.notify_all()

    def wait_for_work(self, timeout: float = 1.0) -> bool:
        with self._lock:
            if self._pending:
                return True
            return self._work.wait(timeout)

    def notify(self) -> None:
        with self._lock:
            self._work.notify_all()

    # -- lifecycle -----------------------------------------------------

    def cancel(self, job_id: str):
        """Cancel a job: queued jobs cancel immediately; running jobs
        get the flag and are dropped at their batch boundary (device
        batches are not interrupted mid-program).  Returns
        (job, cancelled_now) — False when the job was already running
        (flag set) or already terminal (no-op)."""
        job = self.get(job_id)
        with self._lock:
            if job in self._pending:
                self._pending.remove(job)
                job.finish(JobState.CANCELLED)
                return job, True
            if job.state not in TERMINAL:
                job.cancel_requested = True
        return job, False

    def retire(self, job: Job) -> None:
        """Record a terminal job for result pickup, pruning the oldest
        terminal records past ``keep_done``."""
        with self._lock:
            self._done_order.append(job.id)
            while len(self._done_order) > self.keep_done:
                old = self._done_order.pop(0)
                self._jobs.pop(old, None)

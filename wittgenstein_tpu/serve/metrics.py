"""SLO observability for the serving layer.

One ServeMetrics instance per scheduler aggregates the host-side
signals the batching design is judged by:

  * queue depth and admission rejections (backpressure pressure);
  * batch occupancy — packed replicas / capacity — the continuous-
    batching headline (an occupancy of 0 means batching is silently
    disabled; CI's loadgen step fails on it);
  * compile-cache effectiveness, re-exported from the run cache's
    monotonic counters as a hit ratio (the "fixed number of compiles"
    claim, measurable);
  * per-job latency and time-to-first-result quantiles (p50/p99 over a
    bounded reservoir of completed jobs);
  * preemption/resume counts for the priority-interleaving path.

Rendering goes through telemetry.export.PromText into the server's
existing /metrics exposition — one text format, one scrape.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import List, Optional


def quantile(values: List[float], q: float) -> float:
    """Nearest-rank quantile of a non-empty list (0 for empty)."""
    if not values:
        return 0.0
    xs = sorted(values)
    idx = min(len(xs) - 1, max(0, int(round(q * (len(xs) - 1)))))
    return xs[idx]


class ServeMetrics:
    """Thread-safe aggregation; every mutation takes the lock, render()
    reads a consistent snapshot."""

    #: completed-job reservoir bound for the latency quantiles
    WINDOW = 1024

    def __init__(self):
        self._lock = threading.Lock()
        self.jobs_submitted = 0
        self.jobs_completed = 0
        self.jobs_failed = 0
        self.jobs_cancelled = 0
        self.batches_total = 0
        self.replicas_packed_total = 0
        self.replicas_capacity_total = 0
        self.last_occupancy = 0.0
        self.preemptions_total = 0
        self.resumes_total = 0
        self.batch_seconds_total = 0.0
        self._latency_s = deque(maxlen=self.WINDOW)
        self._ttfr_s = deque(maxlen=self.WINDOW)

    # -- observations --------------------------------------------------

    def observe_submit(self) -> None:
        with self._lock:
            self.jobs_submitted += 1

    def observe_job(self, job) -> None:
        from .jobs import JobState

        with self._lock:
            if job.state is JobState.DONE:
                self.jobs_completed += 1
            elif job.state is JobState.FAILED:
                self.jobs_failed += 1
            elif job.state is JobState.CANCELLED:
                self.jobs_cancelled += 1
            if job.finished_at and job.submitted_at:
                self._latency_s.append(job.finished_at - job.submitted_at)
            if job.first_result_at and job.submitted_at:
                self._ttfr_s.append(job.first_result_at - job.submitted_at)

    def observe_batch(
        self, packed: int, capacity: int, seconds: float
    ) -> None:
        with self._lock:
            self.batches_total += 1
            self.replicas_packed_total += packed
            self.replicas_capacity_total += capacity
            self.last_occupancy = packed / capacity if capacity else 0.0
            self.batch_seconds_total += seconds

    def observe_ttfr(self, job) -> None:
        """First progress visible for a still-running job (chunked path
        slices report between device calls)."""
        import time

        with self._lock:
            if job.first_result_at is None:
                job.first_result_at = time.monotonic()
                self._ttfr_s.append(job.first_result_at - job.submitted_at)

    def observe_preemption(self) -> None:
        with self._lock:
            self.preemptions_total += 1

    def observe_resume(self) -> None:
        with self._lock:
            self.resumes_total += 1

    # -- export --------------------------------------------------------

    def latency_quantiles(self) -> dict:
        with self._lock:
            lat = list(self._latency_s)
            ttfr = list(self._ttfr_s)
        return {
            "latency_p50_s": quantile(lat, 0.50),
            "latency_p99_s": quantile(lat, 0.99),
            "ttfr_p50_s": quantile(ttfr, 0.50),
            "ttfr_p99_s": quantile(ttfr, 0.99),
            "samples": len(lat),
        }

    def summary(self, queue_depth: Optional[int] = None) -> dict:
        """The machine-readable SLO snapshot (loadgen report rows)."""
        with self._lock:
            out = {
                "jobs_submitted": self.jobs_submitted,
                "jobs_completed": self.jobs_completed,
                "jobs_failed": self.jobs_failed,
                "jobs_cancelled": self.jobs_cancelled,
                "batches_total": self.batches_total,
                "replicas_packed_total": self.replicas_packed_total,
                "replicas_capacity_total": self.replicas_capacity_total,
                "occupancy_avg": (
                    self.replicas_packed_total / self.replicas_capacity_total
                    if self.replicas_capacity_total
                    else 0.0
                ),
                "last_occupancy": self.last_occupancy,
                "preemptions_total": self.preemptions_total,
                "resumes_total": self.resumes_total,
                "batch_seconds_total": round(self.batch_seconds_total, 4),
            }
        if queue_depth is not None:
            out["queue_depth"] = queue_depth
        out.update(self.latency_quantiles())
        return out

    def add_prometheus(self, p, queue) -> None:
        """Append the witt_serve_* families to a PromText builder."""
        from ..parallel.replica_shard import run_cache_info

        with self._lock:
            p.add("serve_queue_depth", queue.depth(),
                  "pending jobs awaiting dispatch")
            p.add("serve_queue_capacity", queue.max_depth,
                  "admission-control bound on pending jobs")
            p.add("serve_jobs_rejected_total", queue.rejected_total,
                  "jobs refused by admission control", "counter")
            for state, n in (
                ("submitted", self.jobs_submitted),
                ("completed", self.jobs_completed),
                ("failed", self.jobs_failed),
                ("cancelled", self.jobs_cancelled),
            ):
                p.add("serve_jobs_total", n, "job lifecycle counters",
                      "counter", {"state": state})
            p.add("serve_batches_total", self.batches_total,
                  "batched dispatches issued", "counter")
            p.add("serve_batch_replicas_packed_total",
                  self.replicas_packed_total,
                  "live job rows packed onto the replica axis", "counter")
            p.add("serve_batch_replicas_capacity_total",
                  self.replicas_capacity_total,
                  "replica-axis capacity offered by those batches",
                  "counter")
            p.add("serve_batch_occupancy", self.last_occupancy,
                  "packed/capacity of the most recent batch")
            p.add("serve_preemptions_total", self.preemptions_total,
                  "long batches parked for higher-priority work",
                  "counter")
            p.add("serve_resumes_total", self.resumes_total,
                  "parked batches resumed from checkpoint", "counter")
            p.add("serve_batch_seconds_total",
                  round(self.batch_seconds_total, 4),
                  "wall seconds spent in batch dispatches", "counter")
            lat = list(self._latency_s)
            ttfr = list(self._ttfr_s)
        for q in (0.5, 0.99):
            p.add("serve_job_latency_seconds", quantile(lat, q),
                  "submit->finish latency of completed jobs", "gauge",
                  {"quantile": str(q)})
            p.add("serve_time_to_first_result_seconds", quantile(ttfr, q),
                  "submit->first progress/result latency", "gauge",
                  {"quantile": str(q)})
        info = run_cache_info()
        lookups = info["hits"] + info["misses"]
        p.add("serve_compile_cache_hit_ratio",
              (info["hits"] / lookups) if lookups else 0.0,
              "run-cache hit ratio (steady workloads approach 1.0)")

"""SLO observability for the serving layer.

One ServeMetrics instance per scheduler aggregates the host-side
signals the batching design is judged by:

  * queue depth and admission rejections (backpressure pressure);
  * batch occupancy — packed replicas / capacity — the continuous-
    batching headline (an occupancy of 0 means batching is silently
    disabled; CI's loadgen step fails on it);
  * compile-cache effectiveness, re-exported from the run cache's
    monotonic counters as a hit ratio (the "fixed number of compiles"
    claim, measurable);
  * per-job latency and time-to-first-result quantiles (p50/p99 over a
    bounded reservoir of completed jobs);
  * preemption/resume counts for the priority-interleaving path;
  * per-tenant attribution (ticks, device-time share, dropped/fault
    counters — fed by the scheduler's obs.batch_attribution slices)
    and per-run latency samples labelled {run_id, tenant} over a small
    bounded window, so an external scraper can join /metrics to the
    flight-recorder / run-record ledger on run_id without us exporting
    an unbounded label cardinality.

Rendering goes through telemetry.export.PromText into the server's
existing /metrics exposition — one text format, one scrape.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import List, Optional

from ..obs.timeseries import TimeSeriesStore


def quantile(values: List[float], q: float) -> float:
    """Nearest-rank quantile of a non-empty list (0 for empty)."""
    if not values:
        return 0.0
    xs = sorted(values)
    idx = min(len(xs) - 1, max(0, int(round(q * (len(xs) - 1)))))
    return xs[idx]


class ServeMetrics:
    """Thread-safe aggregation; every mutation takes the lock, render()
    reads a consistent snapshot."""

    #: completed-job reservoir bound for the latency quantiles
    WINDOW = 1024
    #: bounded window of per-run labelled latency samples (cardinality
    #: guard: /metrics carries the last RUN_WINDOW runs, the full
    #: ledger lives in the flight recorder / run records)
    RUN_WINDOW = 32

    def __init__(self):
        self._lock = threading.Lock()
        # the in-process metric history the SLO burn-rate engine reads:
        # every observation below also lands here as a windowed sample
        # (series named serve.*, see obs/timeseries.py)
        self.timeseries = TimeSeriesStore()
        self.jobs_submitted = 0
        self.jobs_completed = 0
        self.jobs_failed = 0
        self.jobs_cancelled = 0
        self.jobs_quarantined = 0
        self.batches_total = 0
        self.replicas_packed_total = 0
        self.replicas_capacity_total = 0
        self.last_occupancy = 0.0
        self.preemptions_total = 0
        self.resumes_total = 0
        self.batch_seconds_total = 0.0
        # wave packing: width = lanes busy when a dispatch starts
        self.wave_width_last = 0
        self.wave_width_max = 0
        # lane index -> dispatch count (busy seconds live on the lanes)
        self._lane_dispatches: dict = {}
        # fleet resilience: lane supervision + batch salvage + drain
        self.lane_failures_total = 0
        self.lane_restarts_total = 0
        self.lane_rebinds_total = 0
        self.bindings_expired_total = 0
        self.salvage_batches_total = 0
        self.salvage_runs_total = 0
        self.salvage_seconds_total = 0.0
        self.drains_total = 0
        # done-row harvesting: parked batches compacted to a smaller
        # capacity bucket mid-run, and the padding rows freed by it
        self.harvests_total = 0
        self.harvest_rows_freed_total = 0
        self.rows_done_last = 0
        self._latency_s = deque(maxlen=self.WINDOW)
        self._ttfr_s = deque(maxlen=self.WINDOW)
        # (run_id, tenant, latency_s) of recently completed jobs
        self._recent_runs = deque(maxlen=self.RUN_WINDOW)
        # tenant -> accumulated attribution counters
        self._tenants: dict = {}

    # -- observations --------------------------------------------------

    def observe_submit(self) -> None:
        with self._lock:
            self.jobs_submitted += 1

    def observe_job(self, job) -> None:
        from .jobs import JobState

        with self._lock:
            if job.state is JobState.DONE:
                self.jobs_completed += 1
            elif job.state is JobState.FAILED:
                self.jobs_failed += 1
            elif job.state is JobState.CANCELLED:
                self.jobs_cancelled += 1
            elif job.state is JobState.QUARANTINED:
                self.jobs_quarantined += 1
            if job.finished_at and job.submitted_at:
                lat = job.finished_at - job.submitted_at
                self._latency_s.append(lat)
                run_id = getattr(job, "run_id", None)
                if run_id:
                    tenant = (
                        job.spec.tenant if job.spec is not None else "default"
                    )
                    self._recent_runs.append((run_id, tenant, lat))
            if job.first_result_at and job.submitted_at:
                self._ttfr_s.append(job.first_result_at - job.submitted_at)
        ids = self._job_ids(job)
        if job.started_at and job.submitted_at:
            self.timeseries.observe(
                "serve.queue_wait_s", job.started_at - job.submitted_at,
                ctx=ids,
            )
        if job.state in (JobState.FAILED, JobState.QUARANTINED):
            # the victim's ids ride the sample, so a burn-rate alert off
            # this series names the run that tripped it
            self.timeseries.inc("serve.errors_total", ctx=ids)

    @staticmethod
    def _job_ids(job) -> Optional[dict]:
        run_id = getattr(job, "run_id", None)
        if not run_id:
            return None
        return {
            "run_id": run_id,
            "job_id": getattr(job, "id", None),
            "tenant_id": (
                job.spec.tenant if getattr(job, "spec", None) else None
            ),
        }

    def observe_tenant(self, tenant: str, job_attrib: Optional[dict]) -> None:
        """Fold one completed job's attribution slice into its tenant's
        running totals (scheduler calls this at batch finalize)."""
        if not job_attrib:
            return
        with self._lock:
            t = self._tenants.setdefault(
                tenant,
                {
                    "jobs": 0,
                    "ticks": 0,
                    "dropped": 0,
                    "fault_dropped": 0,
                    "device_time_share_last": 0.0,
                },
            )
            t["jobs"] += 1
            for src, dst in (
                ("ticks", "ticks"),
                ("dropped", "dropped"),
                ("fault_dropped", "fault_dropped"),
            ):
                if job_attrib.get(src) is not None:
                    t[dst] += job_attrib[src]
            if job_attrib.get("device_time_share") is not None:
                t["device_time_share_last"] = job_attrib["device_time_share"]

    def observe_batch(
        self, packed: int, capacity: int, seconds: float
    ) -> None:
        with self._lock:
            self.batches_total += 1
            self.replicas_packed_total += packed
            self.replicas_capacity_total += capacity
            self.last_occupancy = packed / capacity if capacity else 0.0
            self.batch_seconds_total += seconds

    def observe_ttfr(self, job) -> None:
        """First progress visible for a still-running job (chunked path
        slices report between device calls)."""
        import time

        with self._lock:
            if job.first_result_at is None:
                job.first_result_at = time.monotonic()
                ttfr = job.first_result_at - job.submitted_at
                self._ttfr_s.append(ttfr)
            else:
                ttfr = None
        if ttfr is not None:
            self.timeseries.observe(
                "serve.ttfr_s", ttfr, ctx=self._job_ids(job)
            )

    def observe_wave(self, lane: int, width: int) -> None:
        """One dispatch started on ``lane`` while ``width`` lanes were
        busy (this one included) — the wave-packing headline: a steady
        width of G means G families genuinely execute concurrently."""
        with self._lock:
            self.wave_width_last = width
            self.wave_width_max = max(self.wave_width_max, width)
            self._lane_dispatches[lane] = (
                self._lane_dispatches.get(lane, 0) + 1
            )

    def observe_preemption(self) -> None:
        with self._lock:
            self.preemptions_total += 1

    def observe_resume(self) -> None:
        with self._lock:
            self.resumes_total += 1

    # -- fleet resilience ----------------------------------------------

    def observe_lane_failure(self, ctx=None) -> None:
        with self._lock:
            self.lane_failures_total += 1
        self.timeseries.inc("serve.lane_failures_total", ctx=ctx)

    def observe_lane_restart(self, ctx=None) -> None:
        with self._lock:
            self.lane_restarts_total += 1
        self.timeseries.inc("serve.lane_restarts_total", ctx=ctx)

    def observe_rebind(self, n: int = 1) -> None:
        """``n`` sticky family bindings moved off a failed lane."""
        with self._lock:
            self.lane_rebinds_total += n

    def observe_binding_expired(self, n: int = 1) -> None:
        with self._lock:
            self.bindings_expired_total += n

    def observe_salvage(self, runs: int, seconds: float) -> None:
        """One batch salvage completed: ``runs`` probe/re-run dispatches
        costing ``seconds`` of wall time (the salvage overhead
        BENCH_SERVE tracks)."""
        with self._lock:
            self.salvage_batches_total += 1
            self.salvage_runs_total += runs
            self.salvage_seconds_total += seconds

    def observe_drain(self) -> None:
        with self._lock:
            self.drains_total += 1

    def observe_harvest(self, rows_freed: int, ctx=None) -> None:
        """One parked batch compacted into a smaller capacity bucket:
        ``rows_freed`` replica rows stop being re-run each slice."""
        with self._lock:
            self.harvests_total += 1
            self.harvest_rows_freed_total += rows_freed
        self.timeseries.inc("serve.harvests_total", ctx=ctx)

    def observe_rows_done(self, done: int, total: int, ctx=None) -> None:
        """Per-chunk done-row census from the Supervisor's row_watch
        hook (how many member rows have reached their protocol's
        all_done — the signal harvesting efficacy is judged by)."""
        with self._lock:
            self.rows_done_last = done
        self.timeseries.observe("serve.rows_done", float(done), ctx=ctx)
        if total:
            self.timeseries.observe(
                "serve.rows_done_frac", done / total, ctx=ctx
            )

    # -- export --------------------------------------------------------

    def latency_quantiles(self) -> dict:
        with self._lock:
            lat = list(self._latency_s)
            ttfr = list(self._ttfr_s)
        return {
            "latency_p50_s": quantile(lat, 0.50),
            "latency_p99_s": quantile(lat, 0.99),
            "ttfr_p50_s": quantile(ttfr, 0.50),
            "ttfr_p99_s": quantile(ttfr, 0.99),
            "samples": len(lat),
        }

    def summary(self, queue_depth: Optional[int] = None) -> dict:
        """The machine-readable SLO snapshot (loadgen report rows)."""
        with self._lock:
            out = {
                "jobs_submitted": self.jobs_submitted,
                "jobs_completed": self.jobs_completed,
                "jobs_failed": self.jobs_failed,
                "jobs_cancelled": self.jobs_cancelled,
                "jobs_quarantined": self.jobs_quarantined,
                "batches_total": self.batches_total,
                "replicas_packed_total": self.replicas_packed_total,
                "replicas_capacity_total": self.replicas_capacity_total,
                "occupancy_avg": (
                    self.replicas_packed_total / self.replicas_capacity_total
                    if self.replicas_capacity_total
                    else 0.0
                ),
                "last_occupancy": self.last_occupancy,
                "preemptions_total": self.preemptions_total,
                "resumes_total": self.resumes_total,
                "batch_seconds_total": round(self.batch_seconds_total, 4),
                "wave_width_last": self.wave_width_last,
                "wave_width_max": self.wave_width_max,
                "lane_dispatches": dict(self._lane_dispatches),
                "lane_failures_total": self.lane_failures_total,
                "lane_restarts_total": self.lane_restarts_total,
                "lane_rebinds_total": self.lane_rebinds_total,
                "bindings_expired_total": self.bindings_expired_total,
                "salvage_batches_total": self.salvage_batches_total,
                "salvage_runs_total": self.salvage_runs_total,
                "salvage_seconds_total": round(
                    self.salvage_seconds_total, 4
                ),
                "drains_total": self.drains_total,
                "harvests_total": self.harvests_total,
                "harvest_rows_freed_total": self.harvest_rows_freed_total,
                "rows_done_last": self.rows_done_last,
            }
        if queue_depth is not None:
            out["queue_depth"] = queue_depth
        out.update(self.latency_quantiles())
        with self._lock:
            out["tenants"] = {k: dict(v) for k, v in self._tenants.items()}
        return out

    def add_prometheus(self, p, queue) -> None:
        """Append the witt_serve_* families to a PromText builder."""
        from ..parallel.replica_shard import run_cache_info

        with self._lock:
            p.add("serve_queue_depth", queue.depth(),
                  "pending jobs awaiting dispatch")
            p.add("serve_queue_capacity", queue.max_depth,
                  "admission-control bound on pending jobs")
            p.add("serve_jobs_rejected_total", queue.rejected_total,
                  "jobs refused by admission control", "counter")
            for state, n in (
                ("submitted", self.jobs_submitted),
                ("completed", self.jobs_completed),
                ("failed", self.jobs_failed),
                ("cancelled", self.jobs_cancelled),
                ("quarantined", self.jobs_quarantined),
            ):
                p.add("serve_jobs_total", n, "job lifecycle counters",
                      "counter", {"state": state})
            p.add("serve_batches_total", self.batches_total,
                  "batched dispatches issued", "counter")
            p.add("serve_batch_replicas_packed_total",
                  self.replicas_packed_total,
                  "live job rows packed onto the replica axis", "counter")
            p.add("serve_batch_replicas_capacity_total",
                  self.replicas_capacity_total,
                  "replica-axis capacity offered by those batches",
                  "counter")
            p.add("serve_batch_occupancy", self.last_occupancy,
                  "packed/capacity of the most recent batch")
            p.add("serve_preemptions_total", self.preemptions_total,
                  "long batches parked for higher-priority work",
                  "counter")
            p.add("serve_resumes_total", self.resumes_total,
                  "parked batches resumed from checkpoint", "counter")
            p.add("serve_batch_seconds_total",
                  round(self.batch_seconds_total, 4),
                  "wall seconds spent in batch dispatches", "counter")
            p.add("serve_wave_width", self.wave_width_last,
                  "busy dispatch lanes when the last batch started")
            p.add("serve_wave_width_max", self.wave_width_max,
                  "peak concurrent dispatch lanes observed")
            p.add("serve_lane_failures_total", self.lane_failures_total,
                  "lane worker threads that died (exception or injected "
                  "kill)", "counter")
            p.add("serve_lane_restarts_total", self.lane_restarts_total,
                  "lane workers restarted by fleet supervision", "counter")
            p.add("serve_lane_rebinds_total", self.lane_rebinds_total,
                  "sticky family bindings moved off a failed lane",
                  "counter")
            p.add("serve_bindings_expired_total",
                  self.bindings_expired_total,
                  "idle sticky family->lane bindings reclaimed", "counter")
            p.add("serve_quarantined_total", self.jobs_quarantined,
                  "jobs quarantined as poison rows by batch salvage",
                  "counter")
            p.add("serve_salvage_batches_total", self.salvage_batches_total,
                  "failed batches put through salvage bisection", "counter")
            p.add("serve_salvage_runs_total", self.salvage_runs_total,
                  "probe/re-run dispatches issued by salvage", "counter")
            p.add("serve_salvage_seconds_total",
                  round(self.salvage_seconds_total, 4),
                  "wall seconds spent salvaging failed batches", "counter")
            p.add("serve_drains_total", self.drains_total,
                  "graceful drains entered via the admin surface",
                  "counter")
            p.add("serve_harvests_total", self.harvests_total,
                  "parked batches compacted to a smaller capacity "
                  "bucket mid-run", "counter")
            p.add("serve_harvest_rows_freed_total",
                  self.harvest_rows_freed_total,
                  "replica rows freed by done-row harvesting", "counter")
            p.add("serve_rows_done", self.rows_done_last,
                  "member rows at all_done in the most recent chunk sync")
            for lane, n in sorted(self._lane_dispatches.items()):
                p.add("serve_lane_dispatches_total", n,
                      "dispatches issued per lane", "counter",
                      {"lane": str(lane)})
            lat = list(self._latency_s)
            ttfr = list(self._ttfr_s)
            recent = list(self._recent_runs)
            tenants = {k: dict(v) for k, v in self._tenants.items()}
        for q in (0.5, 0.99):
            p.add("serve_job_latency_seconds", quantile(lat, q),
                  "submit->finish latency of completed jobs", "gauge",
                  {"quantile": str(q)})
            p.add("serve_time_to_first_result_seconds", quantile(ttfr, q),
                  "submit->first progress/result latency", "gauge",
                  {"quantile": str(q)})
        # per-run samples on the same family: {run_id, tenant} labels
        # join /metrics to the flight recorder / run records; bounded at
        # RUN_WINDOW recent runs so label cardinality cannot grow
        for run_id, tenant, sec in recent:
            p.add("serve_job_latency_seconds", round(sec, 6),
                  "submit->finish latency of completed jobs", "gauge",
                  {"run_id": run_id, "tenant": tenant})
        for tenant, t in sorted(tenants.items()):
            labels = {"tenant": tenant}
            p.add("serve_tenant_jobs_total", t["jobs"],
                  "completed jobs attributed per tenant", "counter", labels)
            p.add("serve_tenant_ticks_total", t["ticks"],
                  "engine loop ticks attributed to the tenant's replica "
                  "rows", "counter", labels)
            p.add("serve_tenant_dropped_total", t["dropped"],
                  "store-overflow drops on the tenant's rows", "counter",
                  labels)
            p.add("serve_tenant_fault_dropped_total", t["fault_dropped"],
                  "fault-lane suppressions on the tenant's rows", "counter",
                  labels)
            p.add("serve_tenant_device_time_share", t["device_time_share_last"],
                  "tenant share of the most recent batch's live row-ticks",
                  "gauge", labels)
        info = run_cache_info()
        lookups = info["hits"] + info["misses"]
        p.add("serve_compile_cache_hit_ratio",
              (info["hits"] / lookups) if lookups else 0.0,
              "run-cache hit ratio (steady workloads approach 1.0)")

"""Multi-tenant serving layer: jobs -> compatibility families ->
replica-axis batches -> one compiled program per family.

See docs/serving.md for the job model, the compatibility-key
discipline, batching/preemption semantics, and the SLO metric catalog.
"""

from .jobs import (
    DrainingError,
    Job,
    JobQueue,
    JobSpec,
    JobState,
    QueueFullError,
    SERVE_PROTOCOLS,
    UnknownJobError,
    chunk_schedule,
    plan_from_spec,
    serve_protocol,
)
from .metrics import ServeMetrics, quantile
from .scheduler import BatchScheduler, ScenarioFamily, state_digest

__all__ = [
    "BatchScheduler",
    "DrainingError",
    "Job",
    "JobQueue",
    "JobSpec",
    "JobState",
    "QueueFullError",
    "ScenarioFamily",
    "ServeMetrics",
    "SERVE_PROTOCOLS",
    "UnknownJobError",
    "chunk_schedule",
    "plan_from_spec",
    "quantile",
    "serve_protocol",
    "state_digest",
]

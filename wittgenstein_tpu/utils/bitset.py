"""Bitset helpers.

The oracle side uses plain Python ints as bitsets (arbitrary precision, fast
or/and/popcount).  The batched side uses packed uint32 arrays — see
wittgenstein_tpu.ops.bitops for the jnp/pallas twins.

Reference semantics: core utils/BitSetUtils.java:8-13 (`include`).
"""

from __future__ import annotations

import numpy as np


def include(big: int, small: int) -> bool:
    """True iff every bit set in `small` is set in `big`."""
    return (small & ~big) == 0


def cardinality(bits: int) -> int:
    return bits.bit_count()


def to_ids(bits: int) -> list:
    """Ascending indices of the set bits (BitSet.nextSetBit iteration)."""
    res = []
    while bits:
        lsb = bits & -bits
        res.append(lsb.bit_length() - 1)
        bits ^= lsb
    return res


def int_to_packed(bits: int, n_words: int) -> np.ndarray:
    """Python-int bitset -> packed little-endian uint32 words."""
    if bits >> (32 * n_words):
        raise ValueError(f"bitset needs more than {n_words} words")
    out = np.zeros(n_words, dtype=np.uint32)
    for w in range(n_words):
        out[w] = (bits >> (32 * w)) & 0xFFFFFFFF
    return out


def packed_to_int(words: np.ndarray) -> int:
    bits = 0
    for w, v in enumerate(np.asarray(words, dtype=np.uint32).tolist()):
        bits |= int(v) << (32 * w)
    return bits

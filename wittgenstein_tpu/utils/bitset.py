"""Bitset helpers.

The oracle side uses plain Python ints as bitsets (arbitrary precision, fast
or/and/popcount).  The batched side uses packed uint32 arrays — see
wittgenstein_tpu.ops.bitops for the jnp/pallas twins.

Reference semantics: core utils/BitSetUtils.java:8-13 (`include`).
"""

from __future__ import annotations

import numpy as np


def include(big: int, small: int) -> bool:
    """True iff every bit set in `small` is set in `big`."""
    return (small & ~big) == 0


def cardinality(bits: int) -> int:
    return bits.bit_count()


def to_ids(bits: int) -> list:
    """Ascending indices of the set bits (BitSet.nextSetBit iteration)."""
    res = []
    while bits:
        lsb = bits & -bits
        res.append(lsb.bit_length() - 1)
        bits ^= lsb
    return res


def int_to_packed(bits: int, n_words: int) -> np.ndarray:
    """Python-int bitset -> packed little-endian uint32 words."""
    if bits >> (32 * n_words):
        raise ValueError(f"bitset needs more than {n_words} words")
    out = np.zeros(n_words, dtype=np.uint32)
    for w in range(n_words):
        out[w] = (bits >> (32 * w)) & 0xFFFFFFFF
    return out


def packed_to_int(words: np.ndarray) -> int:
    bits = 0
    for w, v in enumerate(np.asarray(words, dtype=np.uint32).tolist()):
        bits |= int(v) << (32 * w)
    return bits


class JavaBitSet:
    """Mutable bitset with java.util.BitSet semantics: value-based equality
    and hashing, get() beyond length() returns False, or/andNot mutate in
    place.  Used by oracle protocols that rely on BitSet aliasing across
    shared message objects (e.g. P2PHandel's checkSigs2)."""

    __slots__ = ("bits",)

    def __init__(self, bits: int = 0):
        self.bits = bits

    def get(self, i: int) -> bool:
        return (self.bits >> i) & 1 == 1

    def set(self, i: int, value: bool = True) -> None:
        if value:
            self.bits |= 1 << i
        else:
            self.bits &= ~(1 << i)

    def or_(self, other: "JavaBitSet") -> None:
        self.bits |= other.bits

    def and_(self, other: "JavaBitSet") -> None:
        self.bits &= other.bits

    def and_not(self, other: "JavaBitSet") -> None:
        self.bits &= ~other.bits

    def cardinality(self) -> int:
        return self.bits.bit_count()

    def length(self) -> int:
        """Highest set bit + 1 (java.util.BitSet.length)."""
        return self.bits.bit_length()

    def is_empty(self) -> bool:
        return self.bits == 0

    def clone(self) -> "JavaBitSet":
        return JavaBitSet(self.bits)

    def __eq__(self, other):
        return isinstance(other, JavaBitSet) and self.bits == other.bits

    def __hash__(self):
        return hash(self.bits)

    def __repr__(self):
        return "{" + ", ".join(str(i) for i in to_ids(self.bits)) + "}"

    @staticmethod
    def from_string(binary: str) -> "JavaBitSet":
        """Bit i set iff binary[i] == '1' (test helper parity)."""
        binary = binary.replace(" ", "")
        bs = JavaBitSet()
        for i, c in enumerate(binary):
            if c == "1":
                bs.set(i)
        return bs

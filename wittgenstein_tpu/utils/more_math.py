"""Small integer-math helpers (reference: core utils/MoreMath.java:5-18)."""

from __future__ import annotations


def log2(x: int) -> int:
    """Floor of log base 2 of a positive int; log2(0) == 0 like the reference
    (31 - Integer.numberOfLeadingZeros treats 0 specially there as -1; the
    reference only calls it on positives)."""
    if x <= 0:
        raise ValueError(f"x={x}")
    return x.bit_length() - 1


def round_pow2(x: int) -> int:
    """Largest power of two <= x (reference rounds down)."""
    if x <= 0:
        raise ValueError(f"x={x}")
    return 1 << (x.bit_length() - 1)

"""Small integer-math helpers (reference: core utils/MoreMath.java:5-18)."""

from __future__ import annotations


def log2(x: int) -> int:
    """Floor of log base 2 of a positive int; raises on x <= 0 like the
    reference."""
    if x <= 0:
        raise ValueError(f"x={x}")
    return x.bit_length() - 1


def round_pow2(x: int) -> int:
    """n rounded UP to the next power of two; n itself if already a power of
    two (reference MoreMath.roundPow2: highestOneBit, << 1 if not exact)."""
    if x <= 0:
        raise ValueError(f"x={x}")
    res = 1 << (x.bit_length() - 1)
    if res != x:
        res <<= 1
    return res

"""Java integer/float semantics helpers used for bit-exact oracle parity."""

from __future__ import annotations

import math

INT_MIN = -(1 << 31)
INT_MAX = (1 << 31) - 1


def i32(x: int) -> int:
    """Wrap to signed 32-bit (Java int overflow semantics)."""
    x &= 0xFFFFFFFF
    return x - 0x100000000 if x >= 0x80000000 else x


def java_abs(x: int) -> int:
    """Math.abs for Java ints: abs(Integer.MIN_VALUE) is still negative."""
    return x if x == INT_MIN else abs(x)


def java_mod(a: int, b: int) -> int:
    """Java % takes the sign of the dividend (Python's takes the divisor's)."""
    return int(math.fmod(a, b))


def java_int_div(a: int, b: int) -> int:
    """Java integer division truncates toward zero."""
    q = abs(a) // abs(b)
    return q if (a >= 0) == (b >= 0) else -q


def jint(x: float) -> int:
    """Java (int) cast of a double: truncation toward zero."""
    return int(x)  # Python int() truncates toward zero


def jround(x: float) -> int:
    """Java Math.round(double) == floor(x + 0.5)."""
    return math.floor(x + 0.5)


def ushift_r(x: int, n: int) -> int:
    """Java >>> on an int32 value."""
    return (x & 0xFFFFFFFF) >> n


def lshift32(x: int, n: int) -> int:
    """Java << on int32, wrapping."""
    return i32(x << n)

from .javarand import JavaRandom
from .gpd import GeneralizedParetoDistribution
from .more_math import log2, round_pow2

__all__ = ["JavaRandom", "GeneralizedParetoDistribution", "log2", "round_pow2"]

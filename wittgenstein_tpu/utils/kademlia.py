"""Kademlia XOR distance (utils/Kademlia.java:5-29 — the only implemented
part of the reference file; the rest is commented-out design notes)."""

from __future__ import annotations


def distance(v1: bytes, v2: bytes) -> int:
    """Bit-length-style XOR distance between two equal-length byte strings:
    the index (from the top) of the highest differing bit, 0 if equal."""
    assert len(v1) == len(v2)
    if v1 == v2:
        return 0
    dist = len(v1) * 8
    for a, b in zip(v1, v2):
        xor = (a ^ b) & 0xFF
        if xor == 0:
            dist -= 8
        else:
            p = 7
            while ((xor >> p) & 0x01) == 0:
                p -= 1
                dist -= 1
            break
    return dist

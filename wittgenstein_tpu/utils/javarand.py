"""Bit-exact reimplementation of java.util.Random (the 48-bit LCG specified
in the JavaDoc), plus java.util.Collections.shuffle.

The reference simulator derives *all* its determinism from a single
`new Random(0)` per network (reference: core Network.java:32).  Implementing
the exact generator lets the oracle engine reproduce the reference's runs
bit-for-bit, which turns the reference's published outputs (e.g. the README
PingPong progression) into executable golden tests for this repo.
"""

from __future__ import annotations

import math

_MULT = 0x5DEECE66D
_ADD = 0xB
_MASK48 = (1 << 48) - 1


def _i32(x: int) -> int:
    """Wrap to signed 32-bit like a Java int."""
    x &= 0xFFFFFFFF
    return x - 0x100000000 if x >= 0x80000000 else x


class JavaRandom:
    __slots__ = ("_seed", "_have_g", "_next_g")

    def __init__(self, seed: int = 0):
        self.set_seed(seed)

    def set_seed(self, seed: int) -> None:
        self._seed = (seed ^ _MULT) & _MASK48
        self._have_g = False
        self._next_g = 0.0

    # -- core generator ----------------------------------------------------
    def _next(self, bits: int) -> int:
        self._seed = (self._seed * _MULT + _ADD) & _MASK48
        return _i32(self._seed >> (48 - bits))

    # -- public API (names follow the Java API) ----------------------------
    def next_int(self, bound: int | None = None) -> int:
        if bound is None:
            return self._next(32)
        if bound <= 0:
            raise ValueError("bound must be positive")
        r = self._next(31)
        m = bound - 1
        if (bound & m) == 0:  # power of two
            return _i32((bound * r) >> 31)
        u = r
        r = u % bound
        while _i32(u - r + m) < 0:
            u = self._next(31)
            r = u % bound
        return r

    def next_long(self) -> int:
        hi = self._next(32)
        lo = self._next(32)
        v = (hi << 32) + lo
        v &= (1 << 64) - 1
        return v - (1 << 64) if v >= (1 << 63) else v

    def next_boolean(self) -> bool:
        return self._next(1) != 0

    def next_double(self) -> float:
        hi = self._next(26)
        lo = self._next(27)
        return ((hi << 27) + lo) / float(1 << 53)

    def next_float(self) -> float:
        return self._next(24) / float(1 << 24)

    def next_gaussian(self) -> float:
        if self._have_g:
            self._have_g = False
            return self._next_g
        while True:
            v1 = 2 * self.next_double() - 1
            v2 = 2 * self.next_double() - 1
            s = v1 * v1 + v2 * v2
            if 0 < s < 1:
                break
        mult = math.sqrt(-2 * math.log(s) / s)
        self._next_g = v2 * mult
        self._have_g = True
        return v1 * mult

    # -- java.util.Collections.shuffle -------------------------------------
    def shuffle(self, lst: list) -> None:
        """In-place Fisher–Yates exactly as Collections.shuffle(list, rnd)."""
        for i in range(len(lst) - 1, 0, -1):
            j = self.next_int(i + 1)
            lst[i], lst[j] = lst[j], lst[i]

"""Generalized Pareto distribution — closed-form inverse CDF.

Matches the reference implementation semantics
(core utils/GeneralizedParetoDistribution.java:31-47): clamping near 0/1 and
the three-branch inverse.  Because the inverse CDF is closed-form it is
directly jittable; `inverse_f_jnp` is the vectorized twin used by the
batched latency kernels.
"""

from __future__ import annotations

import math

_ONE = 0.999999
_ZERO = 0.000001


class GeneralizedParetoDistribution:
    __slots__ = ("shape", "location", "scale")

    def __init__(self, shape: float, location: float, scale: float):
        if scale <= 0.0:
            raise ValueError(f"scale={scale}")
        self.shape = shape
        self.location = location
        self.scale = scale

    def inverse_f(self, y: float) -> float:
        if y < 0.0 or y > 1.0:
            raise ValueError(f"y={y}")
        if y < _ZERO:
            return self.location
        if y > _ONE:
            if self.shape >= 0:
                return math.inf
            return self.location - self.scale / self.shape
        if abs(self.shape) < _ZERO:
            return self.location - self.scale * math.log1p(-y)
        return self.location + self.scale / self.shape * (-1 + (1 - y) ** -self.shape)


def inverse_f_jnp(shape: float, location: float, scale: float, y):
    """Vectorized inverse CDF on a jnp array y in [0, 1].

    Static distribution parameters, traced y.  The y<ZERO / y>ONE clamps are
    expressed with jnp.where so the function stays branch-free under jit.
    """
    import jax.numpy as jnp

    if scale <= 0.0:
        raise ValueError(f"scale={scale}")
    y = jnp.asarray(y)
    if abs(shape) < _ZERO:
        mid = location - scale * jnp.log1p(-jnp.clip(y, 0.0, _ONE))
    else:
        mid = location + scale / shape * (-1.0 + (1.0 - jnp.clip(y, 0.0, _ONE)) ** -shape)
    hi = jnp.inf if shape >= 0 else location - scale / shape
    out = jnp.where(y < _ZERO, location, jnp.where(y > _ONE, hi, mid))
    return out

"""The serve layer (L5): remote control of simulations over HTTP.

The reference's wserver module (IServer.java:10-34, Server.java:20-173,
ws/WServer.java:22-114) is a Spring Boot REST app; this package is the
same contract on the standard library only (http.server) — no web
framework is available in the image, and none is needed:

  * `Server` — the IServer implementation over the explicit protocol
    registry (the reference uses classpath reflection scanning,
    Server.java:57-70; our registry is the same contract made explicit).
  * `WServer`/`serve` — the HTTP mapping of every /w/** endpoint,
    plus a batch-sweep job endpoint (POST /w/sweep) that exposes the
    RunMultipleTimes multi-seed runner remotely — the `wserver` growth
    axis named in BASELINE.json.
  * `ExternalRest` / `ExternalMockImplementation` — the client-side
    External counterparts (server/ExternalRest.java:20-60,
    ExternalMockImplementation.java:13-42): a node's message handling
    delegated to a remote HTTP service, or to a local logging mock.
"""

from .external import ExternalMockImplementation, ExternalRest
from .server import Server
from .ws import WServer, serve, shutdown_server

__all__ = [
    "ExternalMockImplementation",
    "ExternalRest",
    "Server",
    "WServer",
    "serve",
    "shutdown_server",
]

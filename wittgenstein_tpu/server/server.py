"""IServer implementation (reference: wserver Server.java:20-173).

The reference scans the classpath for Protocol subclasses and Message
subtypes with Spring and instantiates them reflectively from WParameters
(Server.java:37-103, :115-126).  Here the protocol registry is explicit
(core.params.protocol_registry — populated by importing
wittgenstein_tpu.protocols) and the message-subtype scan walks the
oracle Message class hierarchy; injection rebuilds messages field-wise,
mirroring Jackson's field-visibility-ANY mapping (ObjectMapperFactory)."""

from __future__ import annotations

import functools
from typing import Dict, List, Optional, Type

from ..core.params import WParameters, protocol_registry
from ..oracle.messages import Message, SendMessage


@functools.lru_cache(maxsize=1)
def _message_types() -> Dict[str, Type[Message]]:
    """All concrete Message subtypes (Server.java:115-126's classpath scan,
    done on the live class hierarchy).  Keys: the qualified
    '<module>.<Class>' name always, plus the simple class name when it is
    unambiguous — several protocols define e.g. their own SendSigs, and a
    silent simple-name collision would inject the wrong class.  Cached:
    the hierarchy is fixed once wittgenstein_tpu.protocols is imported."""
    import wittgenstein_tpu.protocols  # noqa: F401  (registers everything)

    out: Dict[str, Type[Message]] = {}
    ambiguous = set()
    stack = list(Message.__subclasses__())
    while stack:
        c = stack.pop()
        stack.extend(c.__subclasses__())
        out[f"{c.__module__.rsplit('.', 1)[-1]}.{c.__name__}"] = c
        if c.__name__ in out:
            ambiguous.add(c.__name__)
        else:
            out[c.__name__] = c
    for name in ambiguous:
        out.pop(name, None)
    return out


def node_to_dict(n) -> dict:
    """JSON view of a node: the reference serializes all public Node fields
    (Node.java:22-88) plus protocol counters via Jackson."""
    d = {
        "nodeId": n.node_id,
        "x": n.x,
        "y": n.y,
        "cityName": n.city_name,
        "byzantine": n.byzantine,
        "down": n.is_down(),
        "doneAt": n.done_at,
        "msgReceived": n.msg_received,
        "msgSent": n.msg_sent,
        "bytesReceived": n.bytes_received,
        "bytesSent": n.bytes_sent,
        "speedRatio": n.speed_ratio,
        "extraLatency": n.extra_latency,
        "external": str(n.external) if n.external is not None else None,
    }
    return d


def message_from_dict(d: dict) -> Message:
    """Rebuild a message field-wise without calling its constructor —
    the analog of Jackson's field mapping (WServer.java:99-110)."""
    d = dict(d)
    typ = d.pop("type")
    cls = _message_types().get(typ)
    if cls is None:
        hint = [k for k in _message_types() if k.endswith("." + typ)]
        raise KeyError(
            f"unknown or ambiguous message type {typ!r}"
            + (f" — use one of {hint}" if hint else "")
        )
    m = cls.__new__(cls)
    for k, v in d.items():
        setattr(m, k, v)
    return m


class Server:
    """The in-process server core: one live protocol at a time."""

    def __init__(self):
        self._protocol = None

    # -- discovery (Server.java:73-113) --------------------------------------
    def get_protocols(self) -> List[str]:
        import wittgenstein_tpu.protocols  # noqa: F401

        return sorted(protocol_registry.keys())

    def get_protocol_parameters(self, name: str) -> WParameters:
        import wittgenstein_tpu.protocols  # noqa: F401

        return protocol_registry[name].default_params()

    def get_parameters_name(self) -> List[str]:
        import wittgenstein_tpu.protocols  # noqa: F401

        return [r.params_cls.__name__ for r in protocol_registry.values()]

    # -- lifecycle (Server.java:32-70) ---------------------------------------
    def init(self, name: str, parameters: Optional[WParameters] = None) -> None:
        import wittgenstein_tpu.protocols  # noqa: F401

        reg = protocol_registry[name]
        if parameters is None:
            parameters = reg.default_params()
        if isinstance(parameters, dict):
            parameters = reg.params_cls.from_dict(parameters)
        self._protocol = reg.factory(parameters)
        self._protocol.init()

    @property
    def protocol(self):
        if self._protocol is None:
            raise RuntimeError("no protocol initialized — POST /w/network/init first")
        return self._protocol

    def run_ms(self, ms: int) -> None:
        self.protocol.network().run_ms(ms)

    def get_time(self) -> int:
        return self.protocol.network().time

    # -- inspection ----------------------------------------------------------
    def get_node_info(self, node_id: Optional[int] = None):
        net = self.protocol.network()
        if node_id is None:
            return [node_to_dict(n) for n in net.all_nodes]
        return node_to_dict(net.get_node_by_id(node_id))

    def get_messages(self) -> List[dict]:
        # msgs.peekMessages (Network.java:279-287 via WServer.java:67-70)
        return [ei.to_dict() for ei in self.protocol.network().msgs.peek_messages()]

    def get_status(self) -> dict:
        """Live-simulation counter summary (the telemetry tier the
        reference never had): aggregate node counters + the network's
        occupancy census and send-time drop count."""
        net = self.protocol.network()
        nodes = net.all_nodes
        return {
            "protocol": type(self._protocol).__name__,
            "time": net.time,
            "nodeCount": len(nodes),
            "liveNodes": sum(1 for n in nodes if not n.is_down()),
            "doneNodes": sum(1 for n in nodes if n.done_at > 0),
            "msgReceived": sum(n.msg_received for n in nodes),
            "msgSent": sum(n.msg_sent for n in nodes),
            "bytesReceived": sum(n.bytes_received for n in nodes),
            "bytesSent": sum(n.bytes_sent for n in nodes),
            "occupancy": net.occupancy(),
            "dropped": net.dropped,
        }

    def metrics_text(self) -> str:
        """Prometheus text exposition of the live sim (GET /metrics).
        Always renders — an uninitialized server reports only its own
        up-ness, so a scraper can attach before the first init."""
        from ..telemetry.export import PromText

        p = PromText("witt")
        p.add("server_up", 1, "wittgenstein-tpu control server alive")
        self._add_cost_metrics(p)
        if self._protocol is None:
            return p.render()
        s = self.get_status()
        p.add("sim_time_ms", s["time"], "simulated time, ms")
        p.add("nodes", s["nodeCount"], "total nodes")
        p.add("live_nodes", s["liveNodes"], "nodes not down")
        p.add("done_nodes", s["doneNodes"], "nodes with doneAt > 0")
        p.add("node_msg_sent_total", s["msgSent"], "node msgSent sum", "counter")
        p.add(
            "node_msg_received_total",
            s["msgReceived"],
            "node msgReceived sum",
            "counter",
        )
        p.add("node_bytes_sent_total", s["bytesSent"],
              "node bytesSent sum", "counter")
        p.add("node_bytes_received_total", s["bytesReceived"],
              "node bytesReceived sum", "counter")
        p.add(
            "messages_dropped_total",
            s["dropped"],
            "sends filtered at send time (down/partition/discard)",
            "counter",
        )
        occ = s["occupancy"]
        p.add("store_pending", occ["pending_msgs"], "in-flight messages")
        p.add("store_pending_buckets", occ["pending_buckets"], "occupied ms buckets")
        p.add("conditional_tasks", occ["conditional_tasks"], "registered conditional tasks")
        return p.render()

    @staticmethod
    def _add_cost_metrics(p) -> None:
        """witt_run_cache_* (compiled-program cache counters + compile
        seconds, from parallel.replica_shard) and witt_probe_* (TTL'd
        TPU probe verdict, from profiling.probe) — the ISSUE-7 cost/
        visibility families.  Failures never break /metrics: these are
        best-effort observability, rendered as absent when the process
        has no jax / no probe cache."""
        try:
            from ..parallel.replica_shard import run_cache_info

            info = run_cache_info()
            p.add("run_cache_size", info["size"],
                  "cached compiled run programs", "gauge")
            p.add("run_cache_hits_total", info["hits"],
                  "run-cache lookups served from cache", "counter")
            p.add("run_cache_misses_total", info["misses"],
                  "run-cache lookups that built a new entry", "counter")
            p.add("run_cache_evictions_total", info["evictions"],
                  "run-cache entries dropped by the FIFO bound", "counter")
            p.add("run_cache_compiles_total", info["compiles"],
                  "XLA compiles performed by the run cache", "counter")
            p.add("run_cache_compile_seconds_total",
                  round(info["compile_seconds_total"], 3),
                  "wall-clock spent in run-cache XLA compiles", "counter")
        except Exception:
            pass
        try:
            from ..search.driver import search_metrics

            sm = search_metrics()
            p.add("search_generations_total", sm["generations_total"],
                  "adversary-search generations evaluated", "counter")
            p.add("search_evals_total", sm["evals_total"],
                  "adversary-search replica rows evaluated", "counter")
            p.add("search_eval_seconds_total",
                  round(sm["eval_seconds_total"], 3),
                  "wall-clock spent in adversary-search sweeps", "counter")
            p.add("search_pinned_total", sm["pinned_total"],
                  "champions pinned as regression scenarios", "counter")
            p.add("search_best_objective", sm["best_objective"],
                  "last champion objective value seen", "gauge")
        except Exception:
            pass
        try:
            from ..profiling.probe import add_probe_metrics

            add_probe_metrics(p)
        except Exception:
            pass

    # -- control -------------------------------------------------------------
    def start_node(self, node_id: int) -> None:
        self.protocol.network().get_node_by_id(node_id).start()

    def stop_node(self, node_id: int) -> None:
        self.protocol.network().get_node_by_id(node_id).stop()

    def set_external(self, node_id: int, address: str) -> None:
        from .external import ExternalMockImplementation, ExternalRest

        node = self.protocol.network().get_node_by_id(node_id)
        if address == "mock" or address.startswith("mock:"):
            node.external = ExternalMockImplementation(self.protocol.network())
        else:
            node.external = ExternalRest(address)

    def send_message(self, msg) -> None:
        """Inject a SendMessage (Server.java:152-161)."""
        if isinstance(msg, dict):
            inner = msg.get("message")
            if isinstance(inner, dict):
                inner = message_from_dict(inner)
            msg = SendMessage(
                msg["from"], list(msg["to"]), msg["sendTime"],
                msg.get("delayBetweenSend", 0), inner,
            )
        net = self.protocol.network()
        frm = net.get_node_by_id(msg.from_id)
        dests = [net.get_node_by_id(i) for i in msg.to]
        send_time = max(msg.send_time, net.time + 1)
        net.send(msg.message, send_time, frm, dests, msg.delay_between_send)

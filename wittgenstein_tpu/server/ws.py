"""HTTP mapping of the IServer contract (reference ws/WServer.java:22-114)
on the standard library's http.server, plus the batch-sweep job endpoint.

Endpoints (paths kept byte-identical to the reference's @RequestMapping,
including its start/stop asymmetry — /w/nodes/{id}/start vs
/w/network/nodes/{id}/stop):

  GET  /w/protocols                      list registered protocol names
  GET  /w/protocols/{name}               default parameters JSON
  POST /w/network/init/{name}            init from parameters JSON body
  POST /w/network/runMs/{ms}             advance the simulation
  GET  /w/network/time                   current sim time (ms)
  GET  /w/network/status                 counter summary + occupancy/dropped
  GET  /metrics                          Prometheus text exposition
  GET  /w/network/nodes                  all node views
  GET  /w/network/nodes/{id}             one node view
  GET  /w/network/messages               in-flight message views
  POST /w/nodes/{id}/start               restart a node
  POST /w/network/nodes/{id}/stop        stop a node
  POST /w/network/nodes/{id}/external    attach an External (body = address)
  POST /w/network/send                   inject a SendMessage JSON
  PUT  /w/external_sink                  demo external endpoint (ExternalWS)
  POST /w/sweep                          batch sweep: {"protocol", "params",
                                         "runs", "maxTime", "stats"} ->
                                         RunMultipleTimes aggregates
                                         (executes via the job queue; the
                                         handler blocks for the legacy
                                         response shape)
  POST   /w/jobs                         submit a batched job (202; 429 +
                                         Retry-After when the queue is full;
                                         503 + Retry-After while draining)
  GET    /w/jobs                         job list + scheduler status
  GET    /w/jobs/{id}                    job status + streamed progress
  GET    /w/jobs/{id}/result             result (optional ?waitS= blocking;
                                         quarantined jobs answer 422 with
                                         the error-taxonomy kind)
  DELETE /w/jobs/{id}                    cancel (queued: immediate; running:
                                         dropped at the batch boundary)
  GET    /w/health                       liveness + fleet snapshot (always
                                         200 while the process serves HTTP)
  GET    /w/slo                          SLO burn-rate status: per-objective
                                         fast/slow burn, firing/latched
                                         alerts, timeseries digest
  GET    /w/ready                        readiness: 200 when admitting, 503
                                         + Retry-After when draining or the
                                         sim backend is degraded
  POST   /w/admin/drain                  graceful drain: stop admission,
                                         checkpoint-park in-flight batches
  GET    /w/admin/drain                  drain progress (quiescent flag)
  POST   /w/admin/undrain                resume admission + claiming

The simulation core is single-threaded by design (Network.java:10), so all
handlers serialize on one lock.  The /w/jobs surface is the multi-tenant
path (serve/): handlers only touch the queue and job records; one worker
thread packs compatible jobs onto the replica axis — see docs/serving.md.
"""

from __future__ import annotations

import json
import re
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from pathlib import Path
from typing import Optional, Tuple

from ..serve import (
    BatchScheduler,
    DrainingError,
    JobState,
    QueueFullError,
    UnknownJobError,
)
from .server import Server

_STATIC_DIR = Path(__file__).parent / "static"

_ROUTES = []


class RawResponse:
    """A handler result served verbatim instead of json-encoded (the
    /metrics endpoint speaks Prometheus text exposition)."""

    def __init__(self, body: str, content_type: str = "text/plain; charset=utf-8"):
        self.body = body
        self.content_type = content_type


class Response:
    """A json handler result with explicit status + headers — the busy /
    degraded paths answer 503 with a Retry-After estimate instead of
    blocking the client on the simulation lock."""

    def __init__(self, payload, status: int = 200, headers: Optional[dict] = None):
        self.payload = payload
        self.status = status
        self.headers = dict(headers or {})


def route(method: str, pattern: str, locked: bool = True):
    """`locked=False` routes run outside the shared simulation lock (for
    handlers that build their own protocol instances, e.g. /w/sweep)."""
    rx = re.compile("^" + pattern + "$")

    def deco(fn):
        _ROUTES.append((method, rx, fn, locked))
        return fn

    return deco


class WServer:
    """Routing + handler logic; one live Server per instance.

    Durability upgrades (ISSUE 6): runMs executes in SLICES holding the
    simulation lock per slice — other endpoints (status, metrics, nodes)
    interleave between slices instead of starving behind a long run, and
    POST /w/network/interrupt stops the run at the next slice boundary
    with the state consistent (a repeat runMs RESUMES from the current
    sim time — the DES state is durable in-process).  A second runMs
    while one is in flight gets 503 + Retry-After (estimated from the
    in-flight request's EMA pace), and a backend marked degraded (a
    slice raised) answers 503 until re-init."""

    #: sim-ms advanced per lock hold; interrupt/busy checks run between
    RUN_SLICE_MS = 50

    # single-writer by the run_lock latch: only the one in-flight runMs
    # (serialized by run_lock) writes the progress/EMA fields; readers
    # (_retry_after_s) tolerate a stale float (SL1305)
    UNGUARDED_OK = ("_run_started", "_run_ms_total", "_run_rate_s_per_ms")

    def __init__(self, scheduler: Optional[BatchScheduler] = None):
        self.server = Server()
        # multi-tenant job path (serve/): construction is light — the
        # engine families build lazily on first dispatch, and the worker
        # thread starts on first submit
        self.jobs = scheduler or BatchScheduler()
        self.lock = threading.Lock()
        # serializes runMs only (non-blocking acquire -> 503, not queue)
        self.run_lock = threading.Lock()
        self.degraded = False
        self.degraded_reason: Optional[str] = None
        self._interrupt = threading.Event()
        self._run_rate_s_per_ms = 1e-3  # EMA seed: 1 ms wall per sim-ms
        self._run_started: Optional[float] = None
        self._run_ms_total = 0

    def _retry_after_s(self) -> int:
        """Estimated seconds until the in-flight runMs finishes, from
        the EMA pace of completed runs; >= 1 per RFC 9110 semantics."""
        started, total = self._run_started, self._run_ms_total
        if started is None:
            return 1
        remain = total * self._run_rate_s_per_ms - (time.monotonic() - started)
        return max(1, int(remain) + 1)

    # -- handlers ------------------------------------------------------------
    @route("GET", r"/w/protocols")
    def protocols(self, body):
        return self.server.get_protocols()

    @route("GET", r"/w/protocols/(?P<name>[^/]+)")
    def protocol_params(self, body, name):
        p = self.server.get_protocol_parameters(name)
        return json.loads(p.to_json())

    @route("POST", r"/w/network/init/(?P<name>[^/]+)")
    def init(self, body, name):
        params = json.loads(body) if body else None
        self.server.init(name, params)
        # a fresh sim is a fresh backend: clear the degraded latch
        self.degraded = False
        self.degraded_reason = None
        return {"ok": True}

    def _run_ms_sliced(self, ms: int) -> dict:
        """The sliced, interruptible runMs body — executed on a
        scheduler lane (the handler thread only waits).  Each
        RUN_SLICE_MS slice takes the shared lock, so status/metrics
        reads interleave; the degraded latch is set HERE (inside the
        executing thread) so a broken sim is latched even if the
        waiting client has gone away."""
        self._interrupt.clear()
        self._run_started = time.monotonic()
        self._run_ms_total = ms
        try:
            done = 0
            t0 = time.monotonic()
            try:
                while done < ms:
                    step = min(self.RUN_SLICE_MS, ms - done)
                    with self.lock:
                        self.server.run_ms(step)
                    done += step
                    if self._interrupt.is_set() and done < ms:
                        break
            except RuntimeError:
                raise  # uninitialized server (409) — not a backend fault
            except Exception as e:
                # a slice blew up mid-run: latch degraded so clients get
                # an honest 503 (with the reason) until the operator
                # re-inits, instead of racing a broken sim.  The latch
                # is written under the shared lock like every other
                # writer (init holds it via the route dispatcher)
                with self.lock:
                    self.degraded = True
                    self.degraded_reason = f"{type(e).__name__}: {e}"
                raise
            dt = time.monotonic() - t0
            if done:
                self._run_rate_s_per_ms = (
                    0.5 * self._run_rate_s_per_ms + 0.5 * dt / done
                )
            with self.lock:
                net = self.server.protocol.network()
                return {
                    # ok=False + interrupted: a repeat runMs with the
                    # remaining ms RESUMES — sim state is consistent at
                    # every slice boundary
                    "ok": done == ms,
                    "ranMs": done,
                    "requestedMs": ms,
                    "interrupted": done < ms,
                    "time": self.server.get_time(),
                    # status payload telemetry: callers polling runMs see
                    # store pressure and send-time drops without a second
                    # request
                    "occupancy": net.occupancy(),
                    "dropped": net.dropped,
                }
        finally:
            self._run_started = None

    @route("POST", r"/w/network/runMs/(?P<ms>\d+)", locked=False)
    def run_ms(self, body, ms):
        """Interactive advance, routed through the serve/ job queue like
        every other unit of device work (ISSUE 13): the sliced loop runs
        on a scheduler lane, so the fleet has ONE dispatch discipline —
        a runMs takes a lane turn and is paced/preempted against batch
        jobs instead of bypassing them on the handler thread.  The
        handler semantics are unchanged: it blocks for the legacy
        response shape, a second runMs gets 503 + Retry-After (the
        run_lock is the busy latch), and a full queue answers 503 with
        the scheduler's backpressure estimate."""
        ms = int(ms)
        if self.degraded:
            return Response(
                {
                    "error": f"backend degraded: {self.degraded_reason}",
                    "degraded": True,
                },
                503,
                {"Retry-After": "30"},
            )
        if not self.run_lock.acquire(blocking=False):
            return Response(
                {"error": "a runMs is already in progress", "busy": True},
                503,
                {"Retry-After": str(self._retry_after_s())},
            )
        try:
            try:
                job = self.jobs.submit_legacy(
                    lambda: self._run_ms_sliced(ms)
                )
            except QueueFullError as e:
                return Response(
                    {"error": "job queue full", "busy": True},
                    503,
                    {"Retry-After": str(e.retry_after_s)},
                )
            except DrainingError as e:
                return Response(
                    {"error": str(e), "draining": True},
                    503,
                    {"Retry-After": str(e.retry_after_s)},
                )
            if not job.done_event.wait(600.0):
                return Response(
                    {"error": f"runMs job {job.id} did not finish "
                              "within 600s", "jobId": job.id},
                    503,
                    {"Retry-After": str(self.jobs.retry_after_s())},
                )
            if job.state is JobState.FAILED:
                # surface the original exception class so _invoke's
                # status mapping (RuntimeError -> 409, ...) still holds
                if job.exc is not None:
                    raise job.exc
                raise RuntimeError(job.error or "runMs failed")
            return job.result
        finally:
            self.run_lock.release()

    @route("POST", r"/w/network/interrupt", locked=False)
    def interrupt(self, body):
        """Stop an in-flight runMs at its next slice boundary.  Always
        safe: the flag is cleared when the next runMs starts."""
        running = self.run_lock.locked()
        self._interrupt.set()
        return {"ok": True, "running": running}

    @route("GET", r"/w/network/time")
    def get_time(self, body):
        return self.server.get_time()

    @route("GET", r"/w/network/status")
    def status(self, body):
        s = self.server.get_status()
        s["degraded"] = self.degraded
        if self.degraded_reason:
            s["degradedReason"] = self.degraded_reason
        return s

    @route("GET", r"/metrics")
    def metrics(self, body):
        # Prometheus convention: bare /metrics, text format, no /w prefix.
        # One exposition: the oracle-side families plus the serving
        # layer's witt_serve_* SLO families (queue depth, occupancy,
        # latency quantiles, compile-cache hit ratio)
        from ..telemetry.export import PromText

        p = PromText()
        self.jobs.add_prometheus(p)
        return RawResponse(self.server.metrics_text() + p.render())

    # -- multi-tenant job surface (serve/) -----------------------------------
    @route("POST", r"/w/jobs", locked=False)
    def submit_job(self, body):
        """Admit a batched job.  202 + id on success; 429 + Retry-After
        when admission control refuses (queue full) — the client backs
        off instead of wedging an HTTP worker."""
        spec = json.loads(body)
        try:
            job = self.jobs.submit(spec)
        except QueueFullError as e:
            return Response(
                {"error": str(e), "queueFull": True},
                429,
                {"Retry-After": str(e.retry_after_s)},
            )
        except DrainingError as e:
            return Response(
                {"error": str(e), "draining": True},
                503,
                {"Retry-After": str(e.retry_after_s)},
            )
        return Response(
            {
                "id": job.id,
                # the obs spine's correlation id, minted at this
                # admission: join key into flight-recorder events,
                # checkpoint manifests, and /metrics run samples
                "runId": job.run_id,
                "tenant": job.spec.tenant if job.spec else None,
                "state": job.state.value,
                "compat": job.compat,
                "queueDepth": self.jobs.queue.depth(),
            },
            202,
        )

    @route("GET", r"/w/jobs", locked=False)
    def list_jobs(self, body):
        return {
            "scheduler": self.jobs.status(),
            "jobs": [
                {
                    "id": j.id,
                    "runId": j.run_id,
                    "tenant": j.spec.tenant if j.spec else None,
                    "state": j.state.value,
                    "kind": j.kind,
                }
                for j in self.jobs.queue.jobs()
            ],
        }

    @route("GET", r"/w/jobs/(?P<jid>[^/?]+)", locked=False)
    def job_status(self, body, jid):
        try:
            job = self.jobs.queue.get(jid)
        except UnknownJobError:
            return Response({"error": f"no such job {jid!r}"}, 404)
        return job.to_dict()

    @route(
        "GET",
        r"/w/jobs/(?P<jid>[^/?]+)/result(?:\?(?P<query>.*))?",
        locked=False,
    )
    def job_result(self, body, jid, query=None):
        """Result pickup.  ``?waitS=N`` blocks up to N seconds for the
        job to finish (long-poll); otherwise a pending job answers 202
        + Retry-After so clients poll instead of holding sockets."""
        from urllib.parse import parse_qs

        try:
            job = self.jobs.queue.get(jid)
        except UnknownJobError:
            return Response({"error": f"no such job {jid!r}"}, 404)
        wait_s = 0.0
        if query:
            vals = parse_qs(query).get("waitS")
            if vals:
                wait_s = min(float(vals[0]), 600.0)
        if wait_s > 0:
            job.done_event.wait(wait_s)
        if job.state is JobState.DONE:
            return {"id": job.id, "state": job.state.value,
                    "result": job.result}
        if job.state is JobState.FAILED:
            return Response(
                {"id": job.id, "state": job.state.value,
                 "error": job.error, "errorKind": job.error_kind}, 500,
            )
        if job.state is JobState.QUARANTINED:
            # 4xx on purpose: the job's OWN row poisoned its batch
            # (scheduler bisection pinned it) — retrying it verbatim
            # will poison the next batch too, so clients must not
            return Response(
                {"id": job.id, "state": job.state.value,
                 "error": job.error, "errorKind": job.error_kind,
                 "quarantined": True}, 422,
            )
        if job.state is JobState.CANCELLED:
            return Response(
                {"id": job.id, "state": job.state.value}, 410,
            )
        return Response(
            {"id": job.id, "state": job.state.value, "ready": False},
            202,
            {"Retry-After": str(self.jobs.retry_after_s())},
        )

    @route("DELETE", r"/w/jobs/(?P<jid>[^/?]+)", locked=False)
    def cancel_job(self, body, jid):
        try:
            job = self.jobs.cancel(jid)
        except UnknownJobError:
            return Response({"error": f"no such job {jid!r}"}, 404)
        return job.to_dict()

    @route("GET", r"/w/network/nodes")
    def nodes(self, body):
        return self.server.get_node_info()

    @route("GET", r"/w/network/nodes/(?P<nid>\d+)")
    def node(self, body, nid):
        return self.server.get_node_info(int(nid))

    @route("GET", r"/w/network/messages")
    def messages(self, body):
        # the reference returns the bare EnvelopeInfo list; the wrapper
        # adds the engine occupancy census + dropped counter alongside
        # (same upgrade as the runMs status payload)
        net = self.server.protocol.network()
        return {
            "messages": self.server.get_messages(),
            "occupancy": net.occupancy(),
            "dropped": net.dropped,
        }

    @route("POST", r"/w/nodes/(?P<nid>\d+)/start")
    def start_node(self, body, nid):
        self.server.start_node(int(nid))
        return {"ok": True}

    @route("POST", r"/w/network/nodes/(?P<nid>\d+)/stop")
    def stop_node(self, body, nid):
        self.server.stop_node(int(nid))
        return {"ok": True}

    @route("POST", r"/w/network/nodes/(?P<nid>\d+)/external")
    def set_external(self, body, nid):
        address = body.strip().strip('"')
        self.server.set_external(int(nid), address)
        return {"ok": True}

    @route("POST", r"/w/network/send")
    def send(self, body):
        self.server.send_message(json.loads(body))
        return {"ok": True}

    @route("PUT", r"/w/external_sink", locked=False)
    def external_sink(self, body):
        # demo endpoint (ws/ExternalWS.java:22-40): log and return no sends.
        # lock-free: it touches no simulation state, and a node delegated
        # to OUR OWN sink calls back in while runMs holds the lock
        print(f"external_sink received: {body[:200]}")
        return []

    @staticmethod
    def _run_legacy_sweep(spec: dict) -> dict:
        """The original /w/sweep body: run a protocol `runs` times
        (seed = run index, RunMultipleTimes.java:48-63) and return the
        aggregated stats."""
        import wittgenstein_tpu.protocols  # noqa: F401  (fills the registry)

        from ..core import stats as SH
        from ..core.params import protocol_registry
        from ..core.runners import RunMultipleTimes

        reg = protocol_registry[spec["protocol"]]
        params = reg.params_cls.from_dict(spec.get("params", {}))
        p = reg.factory(params)

        getters = []
        for s in spec.get("stats", ["doneAt"]):
            if s == "doneAt":
                getters.append(SH.DoneAtStatGetter())
            elif s == "msgReceived":
                getters.append(SH.MsgReceivedStatGetter())
            else:
                raise KeyError(f"unknown stat {s!r}")
        runner = RunMultipleTimes(
            p, spec.get("runs", 1), spec.get("maxTime", 10_000), getters
        )
        cont = RunMultipleTimes.cont_until_done() if spec.get("untilDone", True) else None
        stats = runner.run(cont)
        out = []
        for g, st in zip(getters, stats):
            out.append({f: st.get(f) for f in g.fields()})
        return {"protocol": spec["protocol"], "runs": spec.get("runs", 1), "stats": out}

    @route("POST", r"/w/sweep", locked=False)
    def sweep(self, body):
        """Batch-sweep job, routed through the serve/ job queue instead
        of running inside this handler thread: the sweep takes one
        worker turn under the scheduler (admission control applies — a
        full queue answers 503 + Retry-After instead of wedging), while
        the handler blocks on the job for the legacy response shape."""
        spec = json.loads(body)
        try:
            job = self.jobs.submit_legacy(
                lambda: self._run_legacy_sweep(spec)
            )
        except QueueFullError as e:
            return Response(
                {"error": str(e), "queueFull": True},
                503,
                {"Retry-After": str(e.retry_after_s)},
            )
        except DrainingError as e:
            return Response(
                {"error": str(e), "draining": True},
                503,
                {"Retry-After": str(e.retry_after_s)},
            )
        job.done_event.wait(600.0)
        if job.exc is not None:
            raise job.exc  # preserve the legacy error mapping (_invoke)
        if job.state is not JobState.DONE:
            return Response(
                {"error": f"sweep job {job.id} did not finish "
                 f"(state={job.state.value})"},
                503,
                {"Retry-After": str(self.jobs.retry_after_s())},
            )
        return job.result

    # -- operational surface (health / readiness / drain) --------------------
    @route("GET", r"/w/health", locked=False)
    def health(self, body):
        """Liveness + fleet snapshot: always 200 while the process can
        serve HTTP (a draining or degraded fleet is still ALIVE — use
        /w/ready for routability).  The payload is the scheduler's full
        operational state: queue pressure, per-lane liveness/restarts,
        drain state, quarantine/salvage counters, compile-store and
        error-taxonomy counters."""
        h = self.jobs.health()
        h["degraded"] = self.degraded
        if self.degraded_reason:
            h["degradedReason"] = self.degraded_reason
        return h

    @route("GET", r"/w/slo", locked=False)
    def slo(self, body):
        """SLO burn-rate status (obs/slo.py): evaluates every
        registered objective against the in-process timeseries NOW
        (evaluation is pull-driven — reading this endpoint IS the
        evaluator) and returns per-SLO burn rows, the latched active
        alerts, cumulative alert counts, and a per-series digest.
        Always 200: a firing SLO is a fact to report, not an error."""
        return self.jobs.slo_status()

    @route("GET", r"/w/ready", locked=False)
    def ready(self, body):
        """Readiness: 200 iff this process should receive NEW work —
        503 + Retry-After while draining (stop sending, finish soon) or
        while the sim backend is degraded (re-init required)."""
        if self.jobs.draining:
            return Response(
                {"ready": False, "reason": "draining",
                 "drain": self.jobs.drain_status()},
                503,
                {"Retry-After": str(self.jobs.retry_after_s())},
            )
        if self.degraded:
            return Response(
                {"ready": False, "reason": "degraded",
                 "error": self.degraded_reason},
                503,
                {"Retry-After": "30"},
            )
        return {"ready": True, "queueDepth": self.jobs.queue.depth()}

    @route("POST", r"/w/admin/drain", locked=False)
    def drain(self, body):
        """Graceful drain: admission starts answering 503 +
        Retry-After, lanes stop claiming, in-flight chunked batches
        checkpoint-stop at their next chunk boundary.  Poll GET
        /w/admin/drain until ``quiescent`` before stopping the process;
        pending jobs and parked checkpoints survive for undrain."""
        return self.jobs.drain()

    @route("GET", r"/w/admin/drain", locked=False)
    def drain_progress(self, body):
        return self.jobs.drain_status()

    @route("POST", r"/w/admin/undrain", locked=False)
    def undrain(self, body):
        return self.jobs.undrain()

    # -- dispatch ------------------------------------------------------------
    def dispatch(self, method: str, path: str, body: str) -> Tuple[int, object]:
        for m, rx, fn, locked in _ROUTES:
            if m != method:
                continue
            mt = rx.match(path)
            if mt:
                if locked:
                    with self.lock:
                        return self._invoke(fn, body, mt.groupdict())
                return self._invoke(fn, body, mt.groupdict())
        return 404, {"error": f"no route {method} {path}"}

    def _invoke(self, fn, body, kwargs) -> Tuple[int, object]:
        try:
            out = fn(self, body, **kwargs)
            if isinstance(out, Response):
                return out.status, out
            return 200, out
        except (KeyError, ValueError, TypeError, AttributeError) as e:
            return 400, {"error": f"{type(e).__name__}: {e}"}
        except RuntimeError as e:
            return 409, {"error": str(e)}
        except Exception as e:  # never drop the socket without a response
            return 500, {"error": f"{type(e).__name__}: {e}"}


class _Handler(BaseHTTPRequestHandler):
    ws: WServer  # set by serve()

    def _respond(
        self, status: int, content_type: str, data: bytes, headers=None
    ):
        self.send_response(status)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(data)))
        for k, v in (headers or {}).items():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(data)

    def _do(self, method: str):
        # the browser UI (analog of the reference's static/index.html,
        # served from wserver resources by spring-boot)
        if method == "GET" and self.path in ("/", "/index.html"):
            try:
                page = (_STATIC_DIR / "index.html").read_bytes()
            except OSError as e:
                self._respond(
                    500, "application/json", json.dumps({"error": str(e)}).encode()
                )
                return
            self._respond(200, "text/html; charset=utf-8", page)
            return
        length = int(self.headers.get("Content-Length") or 0)
        body = self.rfile.read(length).decode() if length else ""
        status, payload = self.ws.dispatch(method, self.path, body)
        if isinstance(payload, RawResponse):
            self._respond(status, payload.content_type, payload.body.encode())
            return
        if isinstance(payload, Response):
            self._respond(
                status,
                "application/json",
                json.dumps(payload.payload).encode(),
                payload.headers,
            )
            return
        self._respond(status, "application/json", json.dumps(payload).encode())

    def do_GET(self):
        self._do("GET")

    def do_POST(self):
        self._do("POST")

    def do_PUT(self):
        self._do("PUT")

    def do_DELETE(self):
        self._do("DELETE")

    def log_message(self, fmt, *args):  # quiet by default
        pass


def serve(port: int = 0, ws: Optional[WServer] = None) -> ThreadingHTTPServer:
    """Start the HTTP server on `port` (0 = ephemeral); returns the server
    (serve_forever runs on a daemon thread; shutdown_server() — or
    .shutdown() — to stop)."""
    ws = ws or WServer()
    handler = type("BoundHandler", (_Handler,), {"ws": ws})
    httpd = ThreadingHTTPServer(("127.0.0.1", port), handler)
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    # keep the handle so shutdown can JOIN the accept loop instead of
    # abandoning a daemon thread mid-accept (simlint SL1304 discipline)
    httpd._witt_serve_thread = t
    return httpd


def shutdown_server(httpd: ThreadingHTTPServer, timeout_s: float = 10.0) -> None:
    """Stop serve_forever AND join its thread — the orderly dual of
    serve().  A plain .shutdown() leaves the daemon thread to die with
    the process; joining makes teardown deterministic for tests and
    smoke scripts."""
    httpd.shutdown()
    t = getattr(httpd, "_witt_serve_thread", None)
    if t is not None:
        t.join(timeout=timeout_s)


if __name__ == "__main__":
    import sys

    port = int(sys.argv[1]) if len(sys.argv) > 1 else 8080
    httpd = serve(port)
    print(f"wittgenstein-tpu server on http://127.0.0.1:{httpd.server_address[1]}/w/protocols")
    try:
        threading.Event().wait()
    except KeyboardInterrupt:
        shutdown_server(httpd)

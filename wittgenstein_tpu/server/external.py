"""Client-side External implementations.

An `External` lets a node's message handling be delegated outside the
simulator (core External.java:7-10; engine hook at Network.java:616-623 —
oracle/network.py's delivery loop): `receive(EnvelopeInfo) ->
List[SendMessage]`.

  * ExternalRest (reference server/ExternalRest.java:20-60): serializes
    the EnvelopeInfo to JSON, PUTs it to a remote service, deserializes
    the returned list of SendMessages.
  * ExternalMockImplementation (ExternalMockImplementation.java:13-42):
    local mock — logs, then executes the message action in-process.
"""

from __future__ import annotations

import json
import urllib.request
from typing import List


class ExternalRest:
    """HTTP client External (ExternalRest.java:24-59)."""

    def __init__(self, http_full_address: str):
        if not http_full_address.startswith("http"):
            http_full_address = "http://" + http_full_address
        self.address = http_full_address

    def __str__(self) -> str:
        return f"ExternalRest({self.address})"

    def receive(self, ei) -> List:
        from .server import message_from_dict
        from ..oracle.messages import SendMessage

        body = json.dumps(ei.to_dict()).encode()
        req = urllib.request.Request(
            self.address, data=body, method="PUT",
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=30) as resp:
            out = json.loads(resp.read().decode() or "[]")
        res = []
        for d in out:
            inner = d.get("message")
            if isinstance(inner, dict):
                inner = message_from_dict(inner)
            # clamp like Server.send_message: a remote naturally answers
            # with sendTime == now, which the engine rejects mid-run
            send_time = max(int(d["sendTime"]), ei.arriving_at + 1)
            res.append(
                SendMessage(
                    d["from"], list(d["to"]), send_time,
                    d.get("delayBetweenSend", 0), inner,
                )
            )
        return res


class ExternalMockImplementation:
    """Logs then executes the action in-process
    (ExternalMockImplementation.java:27-40)."""

    def __init__(self, network):
        self.network = network

    def __str__(self) -> str:
        return type(self).__name__

    def receive(self, ei) -> List:
        print(f"received:{ei.to_dict()}")
        if self.network.time != ei.arriving_at:
            raise ValueError(f"{self.network.time} env:{ei.to_dict()}")
        f = self.network.get_node_by_id(ei.from_id)
        t = self.network.get_node_by_id(ei.to)
        ei.msg.action(self.network, f, t)
        return []

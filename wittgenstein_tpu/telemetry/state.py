"""In-graph telemetry state: the device-side counter tier.

The reference exposes its internals through StatsHelper reductions and
wserver polling — both host-side, both O(host round-trip) per sample.
On the batched engine a host read mid-run would sync the device and
destroy lockstep replica throughput, so the counters live INSIDE the
compiled program as a `TelemetryState` pytree side-car on `SimState`:

  * per-mtype message-store counters (sent / delivered / discarded /
    dropped) updated where the engine already touches the rows —
    `apply_emission` and `_deliver_and_clear`;
  * per-mtype latency-kernel counters (`lat_sent` / `lat_filtered`)
    updated in `latency_arrivals`, so the aggregation protocols whose
    channel messaging bypasses the generic store entirely
    (_agg_batched) still show per-mtype traffic;
  * wheel / overflow high-water marks and the empty-ms jump census —
    the signals bench's `--phase-profile` used to reconstruct post hoc;
  * an optional fixed-size snapshot ring (one slot per
    `snapshot_every_ms` window of sim time) holding (time, done-node
    count, store-pending, cumulative node sent/received) so progress
    curves and time-to-aggregation CDFs come off the device in ONE
    transfer at the end of the run.

Everything here is pure accounting: no field of the simulation proper is
read-modified, no RNG is consumed, so a telemetry-enabled run is
bit-identical in sim state to a disabled one (pinned by
tests/test_telemetry.py).  The enable switch is STATIC (a
`TelemetryConfig` on the engine, part of its jit cache key): disabled
engines carry `tele=()` — an empty pytree, zero leaves, zero traced ops.

Store-counter invariant (tests/test_dropped_invariant.py):

    sent == delivered + discarded + dropped + pending

where `pending` is the live store census (`pending_count`) and
`discarded` counts delivery-time drops (down destination or
cross-partition, Network.java:606) — zero in the standard scenarios.
"""

from __future__ import annotations

import dataclasses
from typing import NamedTuple

import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class TelemetryConfig:
    """Static telemetry knobs; hashable, stamped into the engine's
    cache_key (a different config is a different traced program).

    snapshots: ring slots S for the progress time-series (0 = counters
    only).  One slot per `snapshot_every_ms` window, written at every
    executed tick keyed by `time // every mod S` — a run longer than
    S * every wraps, keeping the most recent S windows (snap_time
    disambiguates; export.progress_series sorts it out)."""

    snapshots: int = 0
    snapshot_every_ms: int = 10

    def __post_init__(self):
        if self.snapshots < 0:
            raise ValueError(f"snapshots={self.snapshots} must be >= 0")
        if self.snapshot_every_ms <= 0:
            raise ValueError(
                f"snapshot_every_ms={self.snapshot_every_ms} must be > 0"
            )

    def key(self) -> tuple:
        return (self.snapshots, self.snapshot_every_ms)


class TelemetryState(NamedTuple):
    """The counter side-car (all int32; leading replica axis appears
    under vmap exactly like every other SimState leaf).  [T] = one row
    per protocol message type; [S] = snapshot ring slots."""

    # message-store counters [T]
    sent: jnp.ndarray  # rows accepted into wheel/overflow
    delivered: jnp.ndarray  # rows removed from the store and delivered
    discarded: jnp.ndarray  # due rows dropped at delivery (down/partition)
    dropped: jnp.ndarray  # per-mtype twin of SimState.dropped (store full)
    # latency-kernel counters [T] (generic ring AND protocol channels)
    lat_sent: jnp.ndarray  # ok sends through latency_arrivals
    lat_filtered: jnp.ndarray  # masked-but-filtered sends (down/partition/
    #                            discard-time, Network.java:476-487)
    # occupancy high-water marks + loop census (scalars)
    wheel_fill_hwm: jnp.ndarray  # max whl_fill ever seen post-insert
    ovf_hwm: jnp.ndarray  # max live overflow entries post-insert
    ticks: jnp.ndarray  # executed engine ticks
    jumps: jnp.ndarray  # empty-ms jumps taken (_step_jump)
    jumped_ms: jnp.ndarray  # total ms skipped by those jumps
    # progress snapshot ring [S] (S may be 0)
    snap_time: jnp.ndarray  # last executed tick in the window, -1 = never
    snap_done: jnp.ndarray  # nodes with done_at > 0
    snap_pending: jnp.ndarray  # store-pending messages (counter diff)
    snap_sent: jnp.ndarray  # cumulative node msg_sent sum
    snap_delivered: jnp.ndarray  # cumulative node msg_received sum


def init_telemetry(cfg: TelemetryConfig, n_msg_types: int) -> TelemetryState:
    t, s = n_msg_types, cfg.snapshots
    zt = lambda: jnp.zeros(t, dtype=jnp.int32)
    zs = lambda: jnp.zeros(s, dtype=jnp.int32)
    return TelemetryState(
        sent=zt(),
        delivered=zt(),
        discarded=zt(),
        dropped=zt(),
        lat_sent=zt(),
        lat_filtered=zt(),
        wheel_fill_hwm=jnp.int32(0),
        ovf_hwm=jnp.int32(0),
        ticks=jnp.int32(0),
        jumps=jnp.int32(0),
        jumped_ms=jnp.int32(0),
        snap_time=jnp.full(s, -1, dtype=jnp.int32),
        snap_done=zs(),
        snap_pending=zs(),
        snap_sent=zs(),
        snap_delivered=zs(),
    )


def count_by_type(counts: jnp.ndarray, mask, mtype_rows) -> jnp.ndarray:
    """counts[T] += per-mtype census of the masked rows (one scatter-add,
    the same shape the engine uses for node counters)."""
    return counts.at[mtype_rows].add(mask.astype(jnp.int32), mode="drop")


def pending_scalar(tele: TelemetryState) -> jnp.ndarray:
    """Store-pending message count as a counter diff — O(T), no store
    scan (the exact census `pending_count` lives in export.py, host
    side; the two agree by the store invariant)."""
    return jnp.sum(tele.sent - tele.delivered - tele.discarded - tele.dropped)


def record_snapshot(
    tele: TelemetryState, cfg: TelemetryConfig, state
) -> TelemetryState:
    """Write this tick's progress sample into its window slot (later
    ticks in the same window overwrite — the slot ends up holding the
    window's LAST executed tick, which equals the window-end state
    because jumped ticks change nothing)."""
    slot = jnp.remainder(
        state.time // cfg.snapshot_every_ms, jnp.int32(cfg.snapshots)
    )
    return tele._replace(
        snap_time=tele.snap_time.at[slot].set(state.time),
        snap_done=tele.snap_done.at[slot].set(
            jnp.sum((state.done_at > 0).astype(jnp.int32))
        ),
        snap_pending=tele.snap_pending.at[slot].set(pending_scalar(tele)),
        snap_sent=tele.snap_sent.at[slot].set(jnp.sum(state.msg_sent)),
        snap_delivered=tele.snap_delivered.at[slot].set(
            jnp.sum(state.msg_received)
        ),
    )

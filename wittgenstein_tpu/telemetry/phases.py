"""Shared per-phase tick profiling: ONE timing loop for both entry
points (bench.py phase_profile() and scripts/phase_profile.py), spans
recorded on the telemetry tracer.

The measurement pattern both callers used to duplicate: jit a
`lax.scan` of `vmap(phase_fn)` over the stacked states, run once to
compile + warm, then time a second run and divide by the scan length.
Phases overlap by construction (delivery is part of the full step), so
the numbers are an op-cost RANKING, not a partition — both callers
document this; keeping the loop here keeps the caveat true in one
place.
"""

from __future__ import annotations

import time
from typing import Callable, Dict, Optional

from .trace import SpanTracer, maybe_span


def scan_phase_seconds(
    states,
    phases: Dict[str, Callable],
    scans: int = 25,
    tracer: Optional[SpanTracer] = None,
) -> Dict[str, float]:
    """Seconds per iteration for each named phase fn (state -> state),
    vmapped over the leading replica axis of `states` and scanned
    `scans` times inside one jit.  Compile+warm and the timed run are
    recorded as spans when a tracer is given."""
    import jax
    from jax import lax

    out: Dict[str, float] = {}
    for name, fn in phases.items():
        def body(s, _, fn=fn):
            return jax.vmap(fn)(s), None

        stepped = jax.jit(lambda s, body=body: lax.scan(body, s, None, length=scans)[0])
        with maybe_span(tracer, "compile+warm", phase=name, scans=scans):
            jax.block_until_ready(stepped(states))
        with maybe_span(tracer, "measure", phase=name, scans=scans):
            t0 = time.perf_counter()
            jax.block_until_ready(stepped(states))
            out[name] = (time.perf_counter() - t0) / scans
    return out


def engine_phase_fns(net) -> Dict[str, Callable]:
    """The engine-generic phase set (what bench's --phase-profile
    reports): full step, delivery+clear, delivery+emission-apply,
    protocol tick, beat."""
    proto = net.protocol
    return {
        "full_step": net.step,
        "delivery": net._phase_deliver,
        "deliver_apply": net._phase_deliver_apply,
        "protocol_tick": lambda s: proto.tick(net, s),
        "beat": lambda s: proto.tick_beat(net, s),
    }

"""Shared per-phase tick profiling: ONE timing loop for both entry
points (bench.py phase_profile() and scripts/phase_profile.py), spans
recorded on the telemetry tracer.

The measurement pattern both callers used to duplicate: jit a
`lax.scan` of `vmap(phase_fn)` over the stacked states, then time
repeated passes and divide by the scan length.  Phases overlap by
construction (delivery is part of the full step), so the numbers are an
op-cost RANKING, not a partition — both callers document this; keeping
the loop here keeps the caveat true in one place.

Warmup discipline (ISSUE-7 satellite): the first post-compile call pays
residual dispatch/executable-load cost that is NOT per-tick work, so
one full pass is run and DISCARDED between compile and measurement, and
the timed passes repeat so each phase reports mean + stddev — an
ablation delta is only trustworthy when it exceeds the measured spread.
"""

from __future__ import annotations

import math
import time
from typing import Callable, Dict, Optional

from .trace import SpanTracer, maybe_span


def scan_phase_seconds(
    states,
    phases: Dict[str, Callable],
    scans: int = 25,
    tracer: Optional[SpanTracer] = None,
    repeats: int = 3,
) -> Dict[str, dict]:
    """Per-iteration timing for each named phase fn (state -> state),
    vmapped over the leading replica axis of `states` and scanned
    `scans` times inside one jit.

    Per phase: one compile pass, one discarded warmup pass (residual
    dispatch — the pre-r11 loop folded it into the measurement), then
    `repeats` timed passes.  Returns
    {name: {mean_s, std_s, min_s, samples_s, scans, repeats}} where the
    *_s values are seconds per scan iteration.  Every pass is recorded
    as a span when a tracer is given."""
    import jax
    from jax import lax

    out: Dict[str, dict] = {}
    repeats = max(1, int(repeats))
    for name, fn in phases.items():
        def body(s, _, fn=fn):
            return jax.vmap(fn)(s), None

        stepped = jax.jit(lambda s, body=body: lax.scan(body, s, None, length=scans)[0])
        with maybe_span(tracer, "compile", phase=name, scans=scans):
            jax.block_until_ready(stepped(states))
        with maybe_span(tracer, "warmup-discarded", phase=name, scans=scans):
            jax.block_until_ready(stepped(states))
        samples = []
        for r in range(repeats):
            with maybe_span(tracer, "measure", phase=name, scans=scans, repeat=r):
                t0 = time.perf_counter()
                jax.block_until_ready(stepped(states))
                samples.append((time.perf_counter() - t0) / scans)
        mean = sum(samples) / len(samples)
        var = sum((x - mean) ** 2 for x in samples) / len(samples)
        out[name] = {
            "mean_s": mean,
            "std_s": math.sqrt(var),
            "min_s": min(samples),
            "samples_s": samples,
            "scans": scans,
            "repeats": repeats,
        }
    return out


def phase_means(stats: Dict[str, dict]) -> Dict[str, float]:
    """Collapse a scan_phase_seconds() result to {name: mean seconds} —
    for callers that only rank phases."""
    return {k: v["mean_s"] for k, v in stats.items()}


def engine_phase_fns(net) -> Dict[str, Callable]:
    """The engine-generic phase set (what bench's --phase-profile
    reports): full step, delivery+clear, delivery+emission-apply,
    protocol tick, beat."""
    proto = net.protocol
    return {
        "full_step": net.step,
        "delivery": net._phase_deliver,
        "deliver_apply": net._phase_deliver_apply,
        "protocol_tick": lambda s: proto.tick(net, s),
        "beat": lambda s: proto.tick_beat(net, s),
    }

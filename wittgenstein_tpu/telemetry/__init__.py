"""Telemetry: device-side counters, progress time-series, host exports.

The observability spine of the TPU rebuild (the StatsHelper/wserver
capability of the reference, SURVEY §L5, captured *inside* jit):

  state.py   TelemetryConfig + TelemetryState — the in-graph counter
             side-car threaded through the engine's send/deliver/jump
             sites, plus the on-device progress-snapshot ring.  Static
             enable: a disabled engine compiles the exact
             pre-telemetry program.
  export.py  host layer — counter summaries, Prometheus text
             exposition, JSONL run records, snapshot-ring decoding
             (progress curves / done-at CDFs in one transfer).
  trace.py   SpanTracer — Chrome trace-event JSON for host phases
             (probe/compile/chunks); complements tools/profiling.py's
             device-level jax.profiler traces.
  phases.py  the shared per-phase tick-cost harness behind
             bench --phase-profile and scripts/phase_profile.py.

Enable on any engine:

    from wittgenstein_tpu.telemetry import TelemetryConfig
    net = BatchedNetwork(proto, latency, n,
                         telemetry=TelemetryConfig(snapshots=128,
                                                   snapshot_every_ms=10))
    out = net.run_ms(state, 1000)
    summary = counters(net, out)          # dict for BENCH/JSONL records
    text = prometheus_from_counters(summary)   # /metrics payload
    series = progress_series(out)              # time/done/pending curve

See docs/telemetry.md for the counter catalog and overhead notes.
"""

from .export import (
    PromText,
    RunRecordWriter,
    counters,
    done_counts_at,
    pending_count,
    progress_series,
    prometheus_from_counters,
    read_run_records,
)
from .phases import engine_phase_fns, phase_means, scan_phase_seconds
from .state import TelemetryConfig, TelemetryState, init_telemetry
from .trace import SpanTracer, maybe_span, validate_chrome_trace

__all__ = [
    "PromText",
    "RunRecordWriter",
    "SpanTracer",
    "TelemetryConfig",
    "TelemetryState",
    "counters",
    "done_counts_at",
    "engine_phase_fns",
    "init_telemetry",
    "maybe_span",
    "pending_count",
    "phase_means",
    "progress_series",
    "prometheus_from_counters",
    "read_run_records",
    "scan_phase_seconds",
    "validate_chrome_trace",
]

"""Host-side span tracer -> Chrome trace-event JSON.

tools/profiling.py wraps jax.profiler (device-level traces for
TensorBoard/Perfetto); this tracer is its HOST complement: explicit,
dependency-free spans for the phases the host controls — backend probe,
compile, warm pass, per-chunk execute — written in the Chrome
trace-event format (the `{"traceEvents": [...]}` JSON object form) so
chrome://tracing, Perfetto and speedscope all open it directly.

    tracer = SpanTracer()
    with tracer.span("compile", nodes=4096):
        compiled = run.lower(states).compile()
    for i in range(n_chunks):
        with tracer.span("chunk", index=i):
            states = compiled(states)
    tracer.write("bench_trace.json")

Spans nest naturally (same tid, enclosing durations) and are
threadsafe — each thread gets its own tid lane.

Correlation: construct with ``ctx=`` (an obs.TraceContext, or any
object with ``.ids() -> dict``, or a plain dict) and every span /
instant carries the run's correlation ids (run_id / job_id /
tenant_id) in its args — the same ids the flight recorder, run
records, checkpoint manifests and serve metrics carry, so a Chrome
trace joins the rest of the ledger on run_id.  Kept duck-typed so this
module stays dependency-free.
"""

from __future__ import annotations

import contextlib
import json
import os
import threading
import time
from typing import Iterator, Optional


class SpanTracer:
    """Collects complete ("ph": "X") trace events with microsecond
    timestamps relative to tracer construction."""

    def __init__(self, process_name: str = "wittgenstein-tpu", ctx=None):
        self._t0 = time.perf_counter()
        self._lock = threading.Lock()
        self._tids = {}  # thread ident -> small stable tid
        self._ctx_ids: dict = {}
        self.events = [
            {
                "ph": "M",
                "name": "process_name",
                "pid": os.getpid(),
                "tid": 0,
                "args": {"name": process_name},
            }
        ]
        if ctx is not None:
            self.set_context(ctx)

    def set_context(self, ctx) -> None:
        """Attach correlation ids (obs.TraceContext, any ``.ids()``
        carrier, or a plain dict): merged into the args of every
        subsequent span/instant, and emitted once as a metadata event
        so the ids survive even in a span-free trace."""
        ids = dict(ctx.ids()) if hasattr(ctx, "ids") else dict(ctx)
        with self._lock:
            self._ctx_ids = ids
            self.events.append(
                {
                    "ph": "M",
                    "name": "trace_context",
                    "pid": os.getpid(),
                    "tid": 0,
                    "args": ids,
                }
            )

    def _with_ctx(self, args: dict) -> dict:
        if not self._ctx_ids:
            return args
        merged = dict(self._ctx_ids)
        merged.update(args)
        return merged

    def _now_us(self) -> float:
        return (time.perf_counter() - self._t0) * 1e6

    def now_us(self) -> float:
        """The tracer's clock (µs since construction) — for callers that
        time work themselves and report via add_span."""
        return self._now_us()

    def _tid(self) -> int:
        ident = threading.get_ident()
        with self._lock:
            return self._tids.setdefault(ident, len(self._tids))

    def add_span(self, name: str, start_us: float, dur_us: float, **args):
        """Record a completed span directly (for spans timed elsewhere)."""
        ev = {
            "ph": "X",
            "name": name,
            "pid": os.getpid(),
            "tid": self._tid(),
            "ts": round(start_us, 1),
            "dur": round(dur_us, 1),
        }
        args = self._with_ctx(args)
        if args:
            ev["args"] = args
        with self._lock:
            self.events.append(ev)
        return ev

    @contextlib.contextmanager
    def span(self, name: str, **args) -> Iterator[None]:
        t0 = self._now_us()
        try:
            yield
        finally:
            self.add_span(name, t0, self._now_us() - t0, **args)

    def instant(self, name: str, **args):
        ev = {
            "ph": "i",
            "name": name,
            "pid": os.getpid(),
            "tid": self._tid(),
            "ts": round(self._now_us(), 1),
            "s": "t",
        }
        args = self._with_ctx(args)
        if args:
            ev["args"] = args
        with self._lock:
            self.events.append(ev)
        return ev

    def to_json(self) -> dict:
        return {"traceEvents": list(self.events), "displayTimeUnit": "ms"}

    def write(self, path: str) -> str:
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        with open(path, "w") as f:
            json.dump(self.to_json(), f)
        return path


def validate_chrome_trace(doc: dict) -> None:
    """Raise ValueError unless `doc` is a well-formed trace-event JSON
    object (the export-format contract the tests pin)."""
    if not isinstance(doc, dict) or "traceEvents" not in doc:
        raise ValueError("not a trace-event JSON object form")
    for ev in doc["traceEvents"]:
        if "ph" not in ev or "name" not in ev:
            raise ValueError(f"event missing ph/name: {ev!r}")
        if ev["ph"] == "X" and ("ts" not in ev or "dur" not in ev):
            raise ValueError(f"complete event missing ts/dur: {ev!r}")


@contextlib.contextmanager
def maybe_span(tracer: Optional[SpanTracer], name: str, **args):
    """Span when a tracer is present, no-op otherwise (lets call sites
    stay unconditional)."""
    if tracer is None:
        yield
    else:
        with tracer.span(name, **args):
            yield

"""Host export layer: one device->host transfer, three wire formats.

The in-graph tier (telemetry.state) accumulates counters on device; this
module turns a FINAL state into:

  * a plain-python counter summary (`counters`) — the BENCH/MULTICHIP
    record payload and the JSONL run-record body;
  * Prometheus text exposition (`PromText` / `prometheus_from_counters`)
    — what the server's /metrics endpoint returns, and what any scrape
    stack ingests directly;
  * a progress time-series (`progress_series` + `done_counts_at`) decoded
    from the on-device snapshot ring — the time-to-aggregation CDF and
    progress curves WITHOUT per-window host reads.

JSONL run records (`RunRecordWriter` / `read_run_records`) are the
durable form: one self-describing line per run, append-only, safe for
concurrent tails (the tpu_campaign jsonl pattern, given a schema).

Nothing here imports the engine — only numpy over pytree leaves — so the
module is import-safe from anywhere (including engine/core.py's own
import of telemetry.state).
"""

from __future__ import annotations

import json
import os
import time
from typing import List, Optional

import numpy as np

RUN_RECORD_SCHEMA = "witt-run-record/v1"


def _py(v):
    """Recursively convert numpy/jax leaves to plain python for json."""
    if isinstance(v, dict):
        return {k: _py(x) for k, x in v.items()}
    if isinstance(v, (list, tuple)):
        return [_py(x) for x in v]
    if hasattr(v, "dtype"):
        a = np.asarray(v)
        if a.ndim == 0:
            return a.item()
        return a.tolist()
    return v


def _mtype_names(protocol) -> List[str]:
    names = list(getattr(protocol, "MSG_TYPES", []) or [])
    n = protocol.n_msg_types() if hasattr(protocol, "n_msg_types") else 1
    while len(names) < n:
        names.append(f"t{len(names)}")
    return names


def pending_count(state) -> int:
    """Exact live-store census (messages, not occupied rows — the
    engine's pending_messages() counts rows for the quiescence test)."""
    return int(
        np.asarray(state.msg_valid).sum() + np.asarray(state.ovf_valid).sum()
    )


def counters(net, state) -> dict:
    """Counter summary of a final state (single replica or batched:
    counts sum over the leading replica axis, high-water marks take the
    max).  Works with telemetry disabled too — the store/latency tiers
    are then absent and only the node-counter block is reported."""
    names = _mtype_names(net.protocol)
    sizes = [int(net.protocol.msg_size(t)) for t in range(len(names))]

    def tsum(a):  # per-mtype arrays: sum replicas, keep the [T] axis
        a = np.asarray(a)
        return a.reshape(-1, a.shape[-1]).sum(axis=0).tolist()

    def ssum(a):
        return int(np.asarray(a).sum())

    def smax(a):
        return int(np.asarray(a).max())

    out = {
        "schema": RUN_RECORD_SCHEMA,
        "telemetry_enabled": net.telemetry is not None,
        "time": smax(state.time),
        "replicas": (
            int(np.asarray(state.time).size)
        ),
        "mtypes": names,
        "msg_sizes": sizes,
        "node": {
            "msg_sent": ssum(state.msg_sent),
            "msg_received": ssum(state.msg_received),
            "bytes_sent": ssum(state.bytes_sent),
            "bytes_received": ssum(state.bytes_received),
            "done_nodes": int((np.asarray(state.done_at) > 0).sum()),
            "down_nodes": int(np.asarray(state.down).sum()),
        },
        "store": {
            "sent_total": ssum(state.msg_head),
            "dropped_total": ssum(state.dropped),
            "pending": pending_count(state),
        },
    }
    if net.telemetry is not None:
        tele = state.tele
        out["store"].update(
            sent=tsum(tele.sent),
            delivered=tsum(tele.delivered),
            discarded=tsum(tele.discarded),
            dropped=tsum(tele.dropped),
        )
        out["latency_kernel"] = {
            "sent": tsum(tele.lat_sent),
            "filtered": tsum(tele.lat_filtered),
            "bytes_sent": [
                int(c) * s for c, s in zip(tsum(tele.lat_sent), sizes)
            ],
        }
        out["occupancy"] = {
            "wheel_fill_hwm": smax(tele.wheel_fill_hwm),
            "overflow_hwm": smax(tele.ovf_hwm),
        }
        # jump efficacy: jumped_ms_frac is the share of simulated
        # milliseconds skipped as provably-empty (per-replica census
        # summed; the denominator is the summed final clocks, i.e. the
        # total ms the batch was billed for).  min/max over replicas
        # bound the spread without an unbounded per-replica list in
        # every record — BENCH's jump-efficacy gate reads the frac
        jumps = np.asarray(tele.jumps).reshape(-1)
        jmd = np.asarray(tele.jumped_ms).reshape(-1)
        out["loop"] = {
            "ticks": ssum(tele.ticks),
            "jumps": ssum(tele.jumps),
            "jumped_ms": ssum(tele.jumped_ms),
            "jumped_ms_frac": round(
                float(jmd.sum())
                / max(1, int(np.asarray(state.time).sum())),
                6,
            ),
            "jumps_min": int(jumps.min()),
            "jumps_max": int(jumps.max()),
            "jumped_ms_min": int(jmd.min()),
            "jumped_ms_max": int(jmd.max()),
        }
    if getattr(net, "faults", None) is not None:
        fs = state.faults
        out["faults"] = {
            "dropped_by_fault": tsum(fs.dropped_by_fault),
            "delayed_by_fault": tsum(fs.delayed_by_fault),
        }
    return out


# -- progress time-series ----------------------------------------------------
def progress_series(state, replica: Optional[int] = None):
    """Decode the snapshot ring into a time-sorted list of
    {time, done, pending, sent, delivered} dicts (unwritten slots are
    dropped; ring wrap is harmless because slots are time-keyed).

    A batched state returns one series per replica (or one series for
    `replica`)."""
    tele = state.tele
    st = np.asarray(tele.snap_time)
    if st.ndim == 2:
        if replica is None:
            return [progress_series(state, r) for r in range(st.shape[0])]
        idx = (replica,)
    else:
        if replica not in (None, 0):
            raise ValueError("single-replica state has only replica 0")
        idx = ()
    cols = {
        "time": st[idx],
        "done": np.asarray(tele.snap_done)[idx],
        "pending": np.asarray(tele.snap_pending)[idx],
        "sent": np.asarray(tele.snap_sent)[idx],
        "delivered": np.asarray(tele.snap_delivered)[idx],
    }
    live = cols["time"] >= 0
    order = np.argsort(cols["time"][live], kind="stable")
    return [
        {k: int(v[live][order][i]) for k, v in cols.items()}
        for i in range(int(live.sum()))
    ]


def done_counts_at(series, times) -> List[int]:
    """Done-node count at each query time, forward-filled between
    snapshots (exact: between two executed ticks nothing changes, the
    engine only jumps time when no event fires)."""
    out = []
    for t in times:
        val = 0
        for row in series:  # series is time-sorted
            if row["time"] <= t:
                val = row["done"]
            else:
                break
        out.append(val)
    return out


# -- Prometheus text exposition ----------------------------------------------
class PromText:
    """Minimal Prometheus text-format (version 0.0.4) renderer: HELP and
    TYPE headers once per metric family, label sets escaped per spec."""

    def __init__(self, prefix: str = "witt"):
        self.prefix = prefix
        self._families = {}  # name -> (type, help, [(labels, value)])

    @staticmethod
    def _esc(v: str) -> str:
        return str(v).replace("\\", "\\\\").replace('"', '\\"').replace(
            "\n", "\\n"
        )

    def add(self, name, value, help="", mtype="gauge", labels=None):
        full = f"{self.prefix}_{name}" if self.prefix else name
        fam = self._families.setdefault(full, (mtype, help, []))
        fam[2].append((dict(labels or {}), value))
        return self

    def render(self) -> str:
        lines = []
        for name, (mtype, help_, samples) in self._families.items():
            if help_:
                lines.append(f"# HELP {name} {self._esc(help_)}")
            lines.append(f"# TYPE {name} {mtype}")
            for labels, value in samples:
                lab = ""
                if labels:
                    inner = ",".join(
                        f'{k}="{self._esc(v)}"' for k, v in labels.items()
                    )
                    lab = "{" + inner + "}"
                v = _py(value)
                lines.append(f"{name}{lab} {v}")
        return "\n".join(lines) + "\n"


def prometheus_from_counters(c: dict, prefix: str = "witt") -> str:
    """Render a `counters()` summary as Prometheus text — the batched
    engine's /metrics payload (the server composes its own oracle-side
    equivalent from the same PromText)."""
    p = PromText(prefix)
    p.add("sim_time_ms", c["time"], "simulated time, ms")
    p.add("replicas", c["replicas"], "stacked replica count")
    n = c["node"]
    p.add("node_msg_sent_total", n["msg_sent"], "node msgSent sum", "counter")
    p.add(
        "node_msg_received_total",
        n["msg_received"],
        "node msgReceived sum",
        "counter",
    )
    p.add("node_bytes_sent_total", n["bytes_sent"],
          "node bytesSent sum", "counter")
    p.add("node_bytes_received_total", n["bytes_received"],
          "node bytesReceived sum", "counter")
    p.add("done_nodes", n["done_nodes"], "nodes with done_at > 0")
    p.add("down_nodes", n["down_nodes"], "dead nodes")
    s = c["store"]
    p.add(
        "store_dropped_total",
        s["dropped_total"],
        "messages lost to store overflow",
        "counter",
    )
    p.add("store_pending", s["pending"], "live messages in the store")
    for key, help_ in (
        ("sent", "rows accepted into the message store"),
        ("delivered", "rows delivered to the protocol"),
        ("discarded", "due rows dropped at delivery"),
        ("dropped", "rows lost to store overflow"),
    ):
        if key in s:
            for name, v in zip(c["mtypes"], s[key]):
                p.add(
                    f"store_{key}_by_type_total",
                    v,
                    help_,
                    "counter",
                    {"mtype": name},
                )
    lk = c.get("latency_kernel")
    if lk:
        for name, v in zip(c["mtypes"], lk["sent"]):
            p.add(
                "messages_sent_total",
                v,
                "ok sends through the latency kernel (store + channels)",
                "counter",
                {"mtype": name},
            )
        for name, v in zip(c["mtypes"], lk["filtered"]):
            p.add(
                "messages_filtered_total",
                v,
                "sends filtered at send time (down/partition/discard)",
                "counter",
                {"mtype": name},
            )
    occ = c.get("occupancy")
    if occ:
        p.add("wheel_fill_hwm", occ["wheel_fill_hwm"], "wheel row fill HWM")
        p.add("overflow_hwm", occ["overflow_hwm"], "overflow lane HWM")
    loop = c.get("loop")
    if loop:
        p.add("ticks_total", loop["ticks"], "executed engine ticks", "counter")
        p.add("jumps_total", loop["jumps"], "empty-ms jumps", "counter")
        p.add("jumped_ms_total", loop["jumped_ms"], "ms skipped", "counter")
        if "jumped_ms_frac" in loop:
            p.add("jumped_ms_frac", loop["jumped_ms_frac"],
                  "share of simulated ms skipped as provably empty")
            for stat in ("jumps_min", "jumps_max",
                         "jumped_ms_min", "jumped_ms_max"):
                p.add(f"loop_{stat}", loop[stat],
                      "per-replica jump census spread")
    fl = c.get("faults")
    if fl:
        for name, v in zip(c["mtypes"], fl["dropped_by_fault"]):
            p.add(
                "fault_dropped_by_type_total",
                v,
                "sends/deliveries suppressed by an injected fault",
                "counter",
                {"mtype": name},
            )
        for name, v in zip(c["mtypes"], fl["delayed_by_fault"]):
            p.add(
                "fault_delayed_by_type_total",
                v,
                "sends whose latency an injected fault rewrote",
                "counter",
                {"mtype": name},
            )
    return p.render()


# -- JSONL run records -------------------------------------------------------
class RunRecordWriter:
    """Append-only JSONL run records: one self-describing line per run
    (ts + schema stamped), numpy leaves converted to plain python.  The
    durable sibling of the BENCH stdout record — tail-safe like
    tpu_campaign.jsonl."""

    def __init__(self, path: str):
        self.path = path

    def write(self, record: dict, **extra) -> dict:
        rec = {"schema": RUN_RECORD_SCHEMA, "ts": round(time.time(), 3)}
        rec.update(_py(record))
        rec.update(_py(extra))
        d = os.path.dirname(os.path.abspath(self.path))
        os.makedirs(d, exist_ok=True)
        with open(self.path, "a") as f:
            f.write(json.dumps(rec) + "\n")
        return rec


def read_run_records(path: str) -> List[dict]:
    """Parse a JSONL run-record file (unparseable lines are skipped, the
    campaign-log convention for torn tails)."""
    out = []
    if os.path.exists(path):
        with open(path) as f:
            for line in f:
                try:
                    out.append(json.loads(line))
                except ValueError:
                    continue
    return out

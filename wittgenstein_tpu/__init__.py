"""wittgenstein_tpu — a TPU-native framework for large-scale simulation of
distributed / consensus protocols.

This is a ground-up, TPU-first rebuild of the capabilities of the
Wittgenstein simulator (reference: /root/reference, pure Java DES).
Instead of a single-threaded discrete-event loop, the compute path is a
time-stepped, batched state transition over struct-of-arrays node state,
`vmap`-ed over simulation replicas and sharded over a `jax.sharding.Mesh`,
so thousands of independent simulations step in lockstep on TPU.

Layout:
  core/       engine primitives: batched tick engine, node state, latency
              models, geo data, registries, parameters
  oracle/     faithful single-threaded DES, bit-exact with the reference
              semantics (java.util.Random included) — the parity oracle
  protocols/  protocol implementations (oracle classes + batched kernels)
  ops/        packed-bitset and queue kernels (jnp + pallas)
  parallel/   device mesh / sharding of the replica and node axes
  runner/     multi-run & progress-per-time drivers, sweeps
  stats/      StatsHelper-equivalent reductions
  telemetry/  in-graph counters + progress snapshot ring (device-side),
              Prometheus / JSONL run-record / Chrome-trace exporters,
              shared phase-profiling harness (docs/telemetry.md)
  tools/      plots, CSV, latency-matrix baking, node drawing
  server/     REST control server (stdlib http)
  utils/      JavaRandom, Pareto distribution, bitset & math helpers
"""

__version__ = "0.1.0"

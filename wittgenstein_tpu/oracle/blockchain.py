"""Blockchain abstractions: blocks, fork-choice-bearing nodes, and a network
that re-floods heads when a partition ends.

Reference semantics: core Block.java / BlockChainNode.java /
BlockChainNetwork.java.
"""

from __future__ import annotations

from typing import Dict, Generic, Optional, Set, TypeVar

from ..core.node import Node, NodeBuilder
from ..utils.javarand import JavaRandom
from .messages import Message
from .network import Network

TB = TypeVar("TB", bound="Block")


class Block:
    """Immutable block; ids are globally unique via a class counter
    (Block.java:10-13).  Use reset_block_ids() between independent runs if id
    determinism across runs matters."""

    _block_id = 1

    @classmethod
    def get_last_block_id(cls) -> int:
        return Block._block_id

    @classmethod
    def reset_block_ids(cls) -> None:
        Block._block_id = 1

    def __init__(
        self,
        producer: Optional["BlockChainNode"] = None,
        height: int = 0,
        parent: Optional["Block"] = None,
        valid: bool = True,
        time: int = 0,
        genesis: bool = False,
    ):
        if genesis:
            self.height = height
            self.last_tx_id = 0
            self.id = 0
            self.parent = None
            self.producer = None
            self.proposal_time = 0
            self.valid = True
            return
        if height <= 0:
            raise ValueError("Only the genesis block has a special height")
        if parent is not None and time < parent.proposal_time:
            raise ValueError(f"bad time: parent is ({parent}), our time:{time}")
        if parent is not None and parent.height >= height:
            raise ValueError(f"Bad parent. me height:{height}, parent:{parent}")
        self.producer = producer
        self.height = height
        self.id = Block._block_id
        Block._block_id += 1
        self.parent = parent
        self.valid = valid
        self.last_tx_id = time
        self.proposal_time = time

    def tx_count(self) -> int:
        if self.id == 0:
            return 0
        assert self.parent is not None
        res = self.last_tx_id - self.parent.last_tx_id
        if res < 0:
            raise RuntimeError(f"{self}, bad txCount:{res}")
        return res

    def is_ancestor(self, b: "Block") -> bool:
        """True if self is a strict ancestor of b (Block.java:75-86)."""
        if self is b:
            return False
        cur = b
        while cur.height > self.height:
            cur = cur.parent
            assert cur is not None
        return cur is self

    def has_direct_link(self, b: "Block") -> bool:
        if b is self:
            return True
        if b.height == self.height:
            return False
        older = self if self.height > b.height else b
        young = self if self.height < b.height else b
        while older.height > young.height:
            older = older.parent
            assert older is not None
        return older is young

    def __repr__(self) -> str:
        if self.id == 0:
            return "genesis"
        return (
            f"h:{self.height}, id={self.id}, creationTime:{self.proposal_time}, "
            f"producer={self.producer.node_id if self.producer else 'null'}, "
            f"parent:{self.parent.id if self.parent else 'null'}"
        )


class BlockChainNode(Node, Generic[TB]):
    __slots__ = (
        "genesis",
        "blocks_received_by_block_id",
        "blocks_received_by_father_id",
        "blocks_received_by_height",
        "head",
    )

    def __init__(self, rd: JavaRandom, nb: NodeBuilder, byzantine: bool, genesis: TB):
        super().__init__(rd, nb, byzantine)
        self.genesis = genesis
        self.blocks_received_by_block_id: Dict[int, TB] = {genesis.id: genesis}
        self.blocks_received_by_father_id: Dict[int, Set[TB]] = {}
        self.blocks_received_by_height: Dict[int, Set[TB]] = {}
        self.head = genesis

    def on_block(self, b: TB) -> bool:
        if not b.valid:
            return False
        if b.id in self.blocks_received_by_block_id:
            return False
        self.blocks_received_by_block_id[b.id] = b
        self.blocks_received_by_father_id.setdefault(b.parent.id, set()).add(b)
        self.blocks_received_by_height.setdefault(b.height, set()).add(b)
        self.head = self.best(self.head, b)
        return True

    def best(self, cur: TB, alt: TB) -> TB:
        """Fork choice; must be provided by the protocol."""
        raise NotImplementedError

    def txs_created_in_chain(self, head: Block) -> int:
        txs = 0
        cur = head
        while cur is not None:
            if cur.producer is self:
                txs += cur.tx_count()
            cur = cur.parent
        return txs

    def blocks_created_in_chain(self, head: Block) -> int:
        blocks = 0
        cur = head
        while cur is not None:
            if cur.producer is self:
                blocks += 1
            cur = cur.parent
        return blocks


class SendBlock(Message):
    def __init__(self, to_send: Block):
        self.to_send = to_send

    def action(self, network, from_node, to_node) -> None:
        to_node.on_block(self.to_send)

    def __repr__(self) -> str:
        return f"SendBlock{{toSend={self.to_send.id}}}"


class BlockChainNetwork(Network):
    """Adds an observer node and full head re-broadcast when a partition
    ends (BlockChainNetwork.java:43-55)."""

    def __init__(self):
        super().__init__()
        self.observer: Optional[BlockChainNode] = None

    def add_observer(self, observer: BlockChainNode) -> None:
        self.observer = observer
        self.add_node(observer)

    def end_partition(self) -> None:
        super().end_partition()
        for n in self.all_nodes:
            self.send_all(SendBlock(n.head), n)

    def print_stat(self, small: bool) -> None:
        production_count: Dict[int, Set[Block]] = {}
        block_producers = []
        cur = self.observer.head
        block_in_chain = 0
        while cur is not self.observer.genesis:
            assert cur is not None and cur.producer is not None
            if not small:
                print(f"block: {cur}")
            block_in_chain += 1
            production_count.setdefault(cur.producer.node_id, set()).add(cur)
            if cur.producer not in block_producers:
                block_producers.append(cur.producer)
            cur = cur.parent
        if not small:
            print(
                f"block count:{block_in_chain} on {Block.get_last_block_id()}, "
                f"all tx: {self.observer.head.last_tx_id}"
            )
        for bp in sorted(block_producers, key=lambda o: o.node_id):
            bp_tx = sum(b.tx_count() for b in production_count[bp.node_id])
            if not small or bp.byzantine:
                print(
                    f"{bp}; {len(production_count[bp.node_id])}; {bp_tx}; "
                    f"{bp.msg_sent}; {bp.msg_received}"
                )

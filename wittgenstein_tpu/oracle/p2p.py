"""P2P overlay: random graph with average-degree or minimum-degree modes.

Reference semantics: core P2PNetwork.java / P2PNode.java, including the
exact RNG consumption order of setPeers (link creation loop, then a
shuffled per-node top-up pass).
"""

from __future__ import annotations

from typing import Dict, List, Set, TypeVar

from ..core.node import Node, NodeBuilder
from ..utils.javarand import JavaRandom
from .messages import FloodMessage
from .network import Network

TP = TypeVar("TP", bound="P2PNode")


class P2PNode(Node):
    __slots__ = ("peers", "_received")

    def __init__(self, rd: JavaRandom, nb: NodeBuilder, byzantine: bool = False):
        super().__init__(rd, nb, byzantine)
        self.peers: List["P2PNode"] = []
        self._received: Dict[int, Set[FloodMessage]] = {}

    def get_msg_received(self, msg_id: int) -> Set[FloodMessage]:
        return self._received.setdefault(msg_id, set())

    def on_flood(self, from_node: "P2PNode", flood_message: FloodMessage) -> None:
        pass


class P2PNetwork(Network[TP]):
    def __init__(self, connection_count: int, minimum: bool):
        super().__init__()
        self._connection_count = connection_count
        self._minimum = minimum
        self._existing_links: Set[tuple] = set()

    def set_peers(self) -> None:
        size = len(self.all_nodes)
        if self._connection_count >= size:
            raise ValueError(
                f"Wrong configuration: #nodes={size}, connection target={self._connection_count}"
            )

        if not self._minimum:
            to_create = (size * self._connection_count) // 2
            while to_create != len(self._existing_links):
                pp1 = self.rd.next_int(size)
                pp2 = self.rd.next_int(size)
                self._create_link(pp1, pp2)

        # Shuffled top-up pass so dead-node clustering doesn't bias degrees
        # (P2PNetwork.java:44-56)
        an = list(self.all_nodes)
        self.rd.shuffle(an)
        target_min = self._connection_count if self._minimum else min(3, self._connection_count)
        for n in an:
            while len(n.peers) < target_min:
                pp2 = self.rd.next_int(size)
                self._create_link(n.node_id, pp2)

    def create_link(self, p1: TP, p2: TP) -> None:
        self._create_link(p1.node_id, p2.node_id)

    def remove_link(self, p1: TP, p2: TP) -> None:
        self._remove_link(p1.node_id, p2.node_id)

    def disconnect(self, p: TP) -> None:
        for n in list(p.peers):
            self.remove_link(p, n)

    def _create_link(self, pp1: int, pp2: int) -> None:
        if pp1 == pp2:
            return
        link = (min(pp1, pp2), max(pp1, pp2))
        if link in self._existing_links:
            return
        self._existing_links.add(link)
        p1, p2 = self.all_nodes[pp1], self.all_nodes[pp2]
        if p1 is None or p2 is None:
            raise RuntimeError(f"should not be null: pp1={pp1}, pp2={pp2}")
        p1.peers.append(p2)
        p2.peers.append(p1)

    def _remove_link(self, pp1: int, pp2: int) -> None:
        if pp1 == pp2:
            return
        link = (min(pp1, pp2), max(pp1, pp2))
        if link not in self._existing_links:
            raise RuntimeError(f"link between {pp1} and {pp2} does not exist")
        self._existing_links.remove(link)
        p1, p2 = self.all_nodes[pp1], self.all_nodes[pp2]
        p1.peers.remove(p2)
        p2.peers.remove(p1)

    def avg_peers(self) -> int:
        if not self.all_nodes:
            return 0
        return sum(len(n.peers) for n in self.all_nodes) // len(self.all_nodes)

    def send_peers(self, msg: FloodMessage, from_node: TP) -> None:
        msg.add_to_received(from_node)
        dest = list(from_node.peers)
        self.rd.shuffle(dest)
        self.send(
            msg, self.time + 1 + msg.local_delay, from_node, dest, msg.delay_between_peers
        )

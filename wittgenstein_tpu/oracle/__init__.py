"""Oracle DES: a faithful single-threaded discrete-event simulator matching
the reference engine's semantics bit-for-bit (same java.util.Random stream,
same per-ms LIFO delivery order, same per-destination jitter hashing).

This is the parity oracle prescribed by SURVEY.md §7 step 2: every batched
TPU kernel is validated against it, first for exact semantics on small runs,
then distributionally (CDF ±1%) at scale.  It is also the debug runner and
the backend for the REST server's interactive mode.
"""

from .messages import (
    ConditionalTask,
    FloodMessage,
    Message,
    PeriodicTask,
    SendMessage,
    StatusFloodMessage,
    Task,
)
from .network import EnvelopeInfo, Network, Protocol
from .p2p import P2PNetwork, P2PNode
from .blockchain import Block, BlockChainNetwork, BlockChainNode

__all__ = [
    "Block",
    "BlockChainNetwork",
    "BlockChainNode",
    "ConditionalTask",
    "EnvelopeInfo",
    "FloodMessage",
    "Message",
    "Network",
    "P2PNetwork",
    "P2PNode",
    "PeriodicTask",
    "Protocol",
    "SendMessage",
    "StatusFloodMessage",
    "Task",
]

"""The oracle discrete-event engine.

Reference semantics: core Network.java (event loop, message storage,
send paths, tasks, partitions) and Envelope.java (single/multi-dest
envelopes with latencies recomputed from a per-envelope random seed).

Exactness notes (each is an observable ordering/determinism invariant):
  * one JavaRandom(0) per network, consumed in the same order as the
    reference (Network.java:32);
  * within one millisecond, deliveries are LIFO in insertion order
    (MsgsSlot head-insertion, Network.java:113-147);
  * multi-dest sends consume ONE random int and derive each destination's
    jitter from getPseudoRandom(destId, seed) — the xorshift hash at
    Network.java:493-503;
  * conditional tasks are polled once per empty millisecond over a snapshot
    taken lazily per nextMessage call (Network.java:533-570);
  * messages to another partition or to/from down nodes are dropped at send
    time, but the sender's counters still tick (Network.java:469-487).
"""

from __future__ import annotations

import functools
from typing import Callable, Dict, Generic, List, Optional, TypeVar

from ..core.latency import IC3NetworkLatency, NetworkLatency
from ..core.node import MAX_X, Node
from ..utils.javaops import i32, java_abs, java_mod, lshift32, ushift_r
from ..utils.javarand import JavaRandom
from .messages import ConditionalTask, Message, PeriodicTask, SendMessage, Task

TN = TypeVar("TN", bound=Node)


def get_pseudo_random(node_id: int, random_seed: int) -> int:
    """Deterministic per-destination delta in [0, 99]
    (Network.getPseudoRandom, Network.java:493-503)."""
    a = i32(node_id)
    a = i32(a ^ lshift32(a, 13))
    a = i32(a ^ ushift_r(a, 17))
    a = i32(a ^ lshift32(a, 5))
    x = i32(a ^ i32(random_seed))
    return java_abs(java_mod(x, 100))


class EnvelopeInfo:
    """Serializable view of an in-flight message (EnvelopeInfo.java)."""

    def __init__(self, from_id: int, to_id: int, sent_at: int, arriving_at: int, msg: Message):
        self.from_id = from_id
        self.to = to_id
        self.sent_at = sent_at
        self.arriving_at = arriving_at
        self.msg = msg

    def _cmp(self, o: "EnvelopeInfo") -> int:
        # Exact port of the (quirky) reference comparator
        # (EnvelopeInfo.java:33-47): several branches re-compare arrivingAt,
        # making them no-ops; the sort is stable, so relative order holds.
        if self.arriving_at != o.arriving_at:
            return -1 if self.arriving_at < o.arriving_at else 1
        if self.sent_at != o.sent_at:
            return 0
        if self.from_id != o.from_id:
            return -1 if self.from_id < o.from_id else 1
        return 0

    sort_key = functools.cmp_to_key(_cmp)

    def to_dict(self) -> dict:
        return {
            "from": self.from_id,
            "to": self.to,
            "sentAt": self.sent_at,
            "arrivingAt": self.arriving_at,
            "msg": type(self.msg).__name__,
        }


# ---------------------------------------------------------------------------
# Envelopes (Envelope.java)
# ---------------------------------------------------------------------------


class _Envelope:
    __slots__ = ("send_time",)

    def __init__(self, send_time: int):
        self.send_time = send_time

    def get_message(self) -> Message: ...
    def next_dest_id(self) -> int: ...
    def next_arrival_time(self, network: "Network") -> int: ...
    def mark_read(self) -> None: ...
    def has_next_reader(self) -> bool: ...
    def from_id(self) -> int: ...
    def infos(self, network: "Network") -> List[EnvelopeInfo]: ...

    def cur_infos(self, network: "Network") -> EnvelopeInfo:
        return EnvelopeInfo(
            self.from_id(),
            self.next_dest_id(),
            self.send_time,
            self.next_arrival_time(network),
            self.get_message(),
        )


class SingleDestEnvelope(_Envelope):
    __slots__ = ("message", "_from_id", "_to_id", "_arrival")

    def __init__(self, message, from_node, to_node, send_time, arrival_time):
        super().__init__(send_time)
        self.message = message
        self._from_id = from_node.node_id
        self._to_id = to_node.node_id
        self._arrival = arrival_time

    def get_message(self):
        return self.message

    def next_dest_id(self):
        return self._to_id

    def next_arrival_time(self, network):
        return self._arrival

    def mark_read(self):
        pass

    def has_next_reader(self):
        return False

    def from_id(self):
        return self._from_id

    def infos(self, network):
        return [
            EnvelopeInfo(self._from_id, self._to_id, self.send_time, self._arrival, self.message)
        ]


class MultipleDestEnvelope(_Envelope):
    """One envelope for thousands of destinations; per-destination latency is
    recomputed on demand from (randomSeed, destId) — the reference's memory
    trick (Envelope.java:46-56), which maps to counter-based RNG on TPU."""

    __slots__ = ("message", "_from_id", "random_seed", "dest_ids", "cur_pos")

    def __init__(self, message, from_node, arrivals, send_time, random_seed):
        super().__init__(send_time)
        self.message = message
        self._from_id = from_node.node_id
        self.random_seed = random_seed
        self.dest_ids = [a[0].node_id for a in arrivals]
        self.cur_pos = 0

    def _arrival_time(self, network: "Network", dest_id: int) -> int:
        delta = get_pseudo_random(dest_id, self.random_seed)
        f = network.get_node_by_id(self._from_id)
        t = network.get_node_by_id(dest_id)
        return self.send_time + network.transit_ms(self.message, f, t, delta)

    def get_message(self):
        return self.message

    def next_dest_id(self):
        return self.dest_ids[self.cur_pos]

    def next_arrival_time(self, network):
        return self._arrival_time(network, self.next_dest_id())

    def mark_read(self):
        self.cur_pos += 1

    def has_next_reader(self):
        return self.cur_pos < len(self.dest_ids)

    def from_id(self):
        return self._from_id

    def infos(self, network):
        return [
            EnvelopeInfo(
                self._from_id,
                d,
                self.send_time,
                self._arrival_time(network, d),
                self.message,
            )
            for d in self.dest_ids[self.cur_pos :]
        ]


class MultipleDestWithDelayEnvelope(_Envelope):
    __slots__ = ("message", "_from_id", "dest_ids", "arrival_times", "cur_pos")

    def __init__(self, message, from_node, arrivals, send_time):
        super().__init__(send_time)
        self.message = message
        self._from_id = from_node.node_id
        self.dest_ids = [a[0].node_id for a in arrivals]
        self.arrival_times = [a[1] for a in arrivals]
        self.cur_pos = 0

    def get_message(self):
        return self.message

    def next_dest_id(self):
        return self.dest_ids[self.cur_pos]

    def next_arrival_time(self, network):
        return self.arrival_times[self.cur_pos]

    def mark_read(self):
        self.cur_pos += 1

    def has_next_reader(self):
        return self.cur_pos < len(self.dest_ids)

    def from_id(self):
        return self._from_id

    def infos(self, network):
        return [
            EnvelopeInfo(self._from_id, d, self.send_time, a, self.message)
            for d, a in zip(self.dest_ids[self.cur_pos :], self.arrival_times[self.cur_pos :])
        ]


# ---------------------------------------------------------------------------
# Message storage: per-ms buckets, LIFO within a bucket
# ---------------------------------------------------------------------------


class MessageStorage:
    """Per-millisecond buckets with LIFO order inside a bucket — semantically
    identical to the reference's rolling slot array (Network.java:116-299);
    the slot machinery there is a Java-heap optimization we don't need."""

    def __init__(self, network: "Network"):
        self._network = network
        self._buckets: Dict[int, List[_Envelope]] = {}

    def add_msg(self, m: _Envelope) -> None:
        na = m.next_arrival_time(self._network)
        if na < self._network.time:
            raise RuntimeError(
                f"Can't add a message arriving in the past! time={self._network.time}, arriving at {na}"
            )
        self._buckets.setdefault(na, []).append(m)

    def peek(self, time: int) -> Optional[_Envelope]:
        lst = self._buckets.get(time)
        return lst[-1] if lst else None

    def poll(self, time: int) -> Optional[_Envelope]:
        lst = self._buckets.get(time)
        if lst:
            m = lst.pop()
            if not lst:
                del self._buckets[time]
            return m
        return None

    def size(self) -> int:
        return sum(len(v) for v in self._buckets.values())

    def size_at(self, time: int) -> int:
        return len(self._buckets.get(time, ()))

    def clear(self) -> None:
        self._buckets.clear()

    def peek_first(self) -> Optional[_Envelope]:
        if not self._buckets:
            return None
        t = min(self._buckets)
        return self._buckets[t][-1]

    def poll_first(self) -> Optional[_Envelope]:
        m = self.peek_first()
        if m is None:
            return None
        return self.poll(m.next_arrival_time(self._network))

    def peek_first_message_content(self) -> Optional[Message]:
        m = self.peek_first()
        return None if m is None else m.get_message()

    def peek_messages(self) -> List[EnvelopeInfo]:
        res: List[EnvelopeInfo] = []
        for t in sorted(self._buckets):
            for m in reversed(self._buckets[t]):  # head-of-chain first
                res.extend(m.infos(self._network))
        res.sort(key=EnvelopeInfo.sort_key)
        return res


# ---------------------------------------------------------------------------
# The Network
# ---------------------------------------------------------------------------


class Network(Generic[TN]):
    def __init__(self):
        self.msgs = MessageStorage(self)
        self.conditional_tasks: List[ConditionalTask] = []
        self.all_nodes: List[TN] = []
        self.rd = JavaRandom(0)
        self.partitions_in_x: List[int] = []
        self.msg_discard_time = 2**31 - 1
        self.network_latency: NetworkLatency = IC3NetworkLatency()
        self.network_throughput = None  # optional Mathis model (opt-in)
        self.time = 0
        # observability (telemetry parity with the batched engine's
        # SimState.dropped / occupancy()): sends filtered at send time —
        # down endpoint, cross-partition, discard-time (the reference
        # drops these silently at Network.java:476-487)
        self.dropped = 0

    # -- helpers -----------------------------------------------------------
    @staticmethod
    def choose_bad_nodes(rd: JavaRandom, node_count: int, nodes_down: int) -> set:
        """Random bad-node set; node 1 always kept up (Network.java:52-64)."""
        bad = set()
        while len(bad) < nodes_down:
            down = rd.next_int(node_count)
            if down != 1 and down not in bad:
                bad.add(down)
        return bad

    def get_node_by_id(self, nid: int) -> TN:
        return self.all_nodes[nid]

    def get_first_live_node(self) -> Optional[TN]:
        for n in self.all_nodes:
            if not n.is_down():
                return n
        return None

    def get_dead_nodes(self) -> set:
        return {n.node_id for n in self.all_nodes if n.is_down()}

    def live_nodes(self) -> List[TN]:
        return [n for n in self.all_nodes if not n.is_down()]

    def set_msg_discard_time(self, t: int) -> "Network[TN]":
        self.msg_discard_time = t
        return self

    def has_message(self) -> bool:
        return self.msgs.size() != 0

    def occupancy(self) -> dict:
        """Store census, shape-compatible with the batched engine's
        occupancy() (wserver surfaces both through the same endpoints)."""
        return {
            "pending_msgs": self.msgs.size(),
            "pending_buckets": len(self.msgs._buckets),
            "conditional_tasks": len(self.conditional_tasks),
        }

    # -- time --------------------------------------------------------------
    def run(self, seconds: int) -> bool:
        return self.run_ms(seconds * 1000)

    def run_ms(self, ms: int) -> bool:
        if ms <= 0:
            raise ValueError(f"Should be greater than 0. ms={ms}")
        if self.time == 0:
            for n in self.all_nodes:
                if not n.is_down():
                    n.start()
        end_at = self.time + ms
        did_something = self._receive_until(end_at)
        self.time = end_at
        return did_something

    # -- send paths --------------------------------------------------------
    def send_all(self, m: Message, from_node: TN, send_time: Optional[int] = None) -> None:
        if send_time is None:
            send_time = self.time + 1
        self.send(m, send_time, from_node, self.all_nodes)

    def send(self, m: Message, a, b, c=None, delays_between_message: int = 0) -> None:
        """Overload resolution mirroring the Java API:
        send(m, fromNode, toNode) / send(m, fromNode, dests) /
        send(m, sendTime, fromNode, toNode) / send(m, sendTime, fromNode, dests[, delay])."""
        if isinstance(a, int):
            send_time, from_node, dest = a, b, c
        else:
            send_time, from_node, dest = self.time + 1, a, b
            if isinstance(dest, list):
                if not dest:
                    return
                if len(dest) == 1:
                    dest = dest[0]
        if isinstance(dest, list):
            self._send_multi(m, send_time, from_node, dest, delays_between_message)
        else:
            self._send_single(m, send_time, from_node, dest)

    def _check_in_network(self, n: Node) -> None:
        if n.node_id >= len(self.all_nodes) or self.all_nodes[n.node_id] is not n:
            raise ValueError(f"The node is not in the network: {n}")

    def _send_single(self, mc: Message, send_time: int, from_node: TN, to_node: TN) -> None:
        self._check_in_network(from_node)
        self._check_in_network(to_node)
        ms = self._create_message_arrival(mc, from_node, to_node, send_time, self.rd.next_int())
        if ms is not None:
            self.msgs.add_msg(
                SingleDestEnvelope(mc, from_node, to_node, send_time, ms[1])
            )

    def _send_multi(
        self, m: Message, send_time: int, from_node: TN, dests: List[TN], delays: int
    ) -> None:
        self._check_in_network(from_node)
        random_seed = self.rd.next_int()
        da = self._create_message_arrivals(m, send_time, from_node, dests, random_seed, delays)
        if not da:
            return
        if len(da) == 1:
            dest, arrival = da[0]
            env: _Envelope = SingleDestEnvelope(m, from_node, dest, send_time, arrival)
        elif delays == 0:
            env = MultipleDestEnvelope(m, from_node, da, send_time, random_seed)
        else:
            env = MultipleDestWithDelayEnvelope(m, from_node, da, send_time)
        self.msgs.add_msg(env)

    def send_arrive_at(self, mc: Message, arrive_at: int, from_node: TN, to_node: TN) -> None:
        if arrive_at <= self.time:
            raise ValueError(f"wrong arrival time: arriveAt={arrive_at}, time={self.time}")
        self.msgs.add_msg(SingleDestEnvelope(mc, from_node, to_node, self.time, arrive_at))

    def _create_message_arrivals(
        self, m, send_time, from_node, dests, random_seed, delays
    ) -> List[tuple]:
        da = []
        for n in dests:
            ma = self._create_message_arrival(m, from_node, n, send_time, random_seed)
            send_time += delays + (1 if delays > 0 else 0)
            if ma is not None:
                da.append(ma)
        da.sort(key=lambda x: x[1])  # stable, by arrival only (Java parity)
        return da

    def _create_message_arrival(
        self, m, from_node: Node, to_node: Node, send_time: int, random_seed: int
    ) -> Optional[tuple]:
        if send_time <= self.time:
            raise RuntimeError(f"{m}, sendTime={send_time}, time={self.time}")
        assert not isinstance(m, Task)
        from_node.msg_sent += 1
        from_node.bytes_sent += m.size()
        if (
            self.partition_id(from_node) == self.partition_id(to_node)
            and not from_node.is_down()
            and not to_node.is_down()
        ):
            nt = self.transit_ms(
                m, from_node, to_node, get_pseudo_random(to_node.node_id, random_seed)
            )
            if nt < self.msg_discard_time:
                return (to_node, send_time + nt)
        self.dropped += 1
        return None

    # -- tasks -------------------------------------------------------------
    def register_task(self, task: Callable[[], None], start_at: int, from_node: TN) -> None:
        sw = Task(task)
        self.msgs.add_msg(SingleDestEnvelope(sw, from_node, from_node, self.time, start_at))

    def register_periodic_task(
        self, task, start_at: int, period: int, from_node: TN, condition=None
    ) -> None:
        sw = PeriodicTask(task, from_node, period, condition)
        self.msgs.add_msg(SingleDestEnvelope(sw, from_node, from_node, self.time, start_at))

    def register_conditional_task(
        self, task, start_at: int, duration: int, from_node: TN, start_if, repeat_if
    ) -> None:
        self.conditional_tasks.append(
            ConditionalTask(start_if, repeat_if, task, start_at, from_node, duration)
        )

    # -- event loop --------------------------------------------------------
    def _next_message(self, until: int) -> Optional[_Envelope]:
        cts: Optional[List[ConditionalTask]] = None
        while self.time <= until:
            m = self.msgs.poll(self.time)
            if m is not None:
                return m
            self.time += 1
            if cts is None:
                cts = list(self.conditional_tasks)
            i = 0
            while i < len(cts):
                ct = cts[i]
                if ct.min_start_time > until or ct.from_node.is_down():
                    cts.pop(i)
                    continue
                if ct.min_start_time <= self.time:
                    cts.pop(i)
                    if ct.start_if():
                        ct.r()
                        ct.min_start_time = self.time + ct.duration
                        if not ct.repeat_if():
                            try:
                                self.conditional_tasks.remove(ct)
                            except ValueError:
                                pass
                    continue
                i += 1
        return None

    def _receive_until(self, until: int) -> bool:
        previous_time = self.time
        next_env = self._next_message(until)
        if next_env is None:
            return False
        while next_env is not None:
            m = next_env
            na = m.next_arrival_time(self)
            if na != previous_time and self.time > na:
                raise RuntimeError(f"time:{self.time}, arrival={na}, m:{m}")

            from_node = self.all_nodes[m.from_id()]
            to_node = self.all_nodes[m.next_dest_id()]

            if not to_node.is_down() and self.partition_id(from_node) == self.partition_id(
                to_node
            ):
                msg = m.get_message()
                if not isinstance(msg, Task):
                    if msg.size() == 0:
                        raise RuntimeError(f"Message size should be greater than zero: {m}")
                    to_node.msg_received += 1
                    to_node.bytes_received += msg.size()
                if to_node.external is not None:
                    ei = m.cur_infos(self)
                    sms: List[SendMessage] = to_node.external.receive(ei)
                    for sm in sms:
                        dest = [self.get_node_by_id(i) for i in sm.to]
                        self.send(
                            sm.message,
                            sm.send_time,
                            self.get_node_by_id(sm.from_id),
                            dest,
                            sm.delay_between_send,
                        )
                else:
                    msg.action(self, from_node, to_node)

            m.mark_read()
            if m.has_next_reader():
                self.msgs.add_msg(m)
            previous_time = self.time
            next_env = self._next_message(until)
        return True

    # -- partitions --------------------------------------------------------
    def partition_id(self, node: Node) -> int:
        pid = 0
        for x in self.partitions_in_x:
            if x > node.x:
                return pid
            pid += 1
        return pid

    def partition(self, part: float) -> None:
        if part <= 0 or part >= 1:
            raise ValueError("part needs to be a percentage between 0 & 100 excluded")
        x_point = int(MAX_X * part)
        if x_point in self.partitions_in_x:
            raise ValueError("this partition exists already")
        self.partitions_in_x.append(x_point)
        self.partitions_in_x.sort()

    def end_partition(self) -> None:
        self.partitions_in_x.clear()

    # -- population --------------------------------------------------------
    def add_node(self, node: TN) -> None:
        while len(self.all_nodes) <= node.node_id:
            self.all_nodes.append(None)  # type: ignore[arg-type]
        if self.all_nodes[node.node_id] is not None:
            raise RuntimeError(f"There is already a node with this id ({node.node_id})")
        self.all_nodes[node.node_id] = node

    def set_network_latency(self, nl) -> "Network[TN]":
        if self.msgs.size() != 0:
            raise RuntimeError(
                "You can't change the latency while the system as on going messages"
            )
        if isinstance(nl, tuple):
            from ..core.latency import MeasuredNetworkLatency

            nl = MeasuredNetworkLatency(nl[0], nl[1])
        self.network_latency = nl
        return self

    def set_network_throughput(self, tp) -> "Network[TN]":
        """Enable TCP-throughput-aware delays (MathisNetworkThroughput):
        message transit becomes size-dependent.  The reference defines the
        model (NetworkThroughput.java:17-57) but never wires it into its
        Network; making it enableable is this rebuild's upgrade."""
        if self.msgs.size() != 0:
            raise RuntimeError(
                "You can't change the throughput while the system as on going messages"
            )
        self.network_throughput = tp
        return self

    def transit_ms(self, m, from_node, to_node, delta: int) -> int:
        """One-way transit time: latency, or the Mathis size-dependent
        delay when a throughput model is set."""
        if self.network_throughput is not None:
            return self.network_throughput.delay(
                from_node, to_node, delta, m.size(), nl=self.network_latency
            )
        return self.network_latency.get_latency(from_node, to_node, delta)


class Protocol:
    """Contract per core Protocol.java: network(), copy(), init(); plus the
    registry convention of a constructor taking one parameters object."""

    def network(self) -> Network:
        raise NotImplementedError

    def copy(self) -> "Protocol":
        raise NotImplementedError

    def init(self) -> None:
        raise NotImplementedError

"""Message hierarchy for the oracle DES.

Reference semantics: core messages/*.java.  Messages are immutable and may
be shared between many in-flight deliveries (multi-dest envelopes).
"""

from __future__ import annotations

from typing import Callable, List, Optional, TYPE_CHECKING

if TYPE_CHECKING:
    from .network import Network
    from .p2p import P2PNode


class Message:
    """action() is the protocol callback on delivery (Message.java:21);
    size() feeds the traffic counters (default 1)."""

    def action(self, network: "Network", from_node, to_node) -> None:
        raise NotImplementedError

    def size(self) -> int:
        return 1

    def __repr__(self) -> str:
        fields = ", ".join(
            f"{k}={v}" for k, v in vars(self).items() if not k.startswith("_")
        )
        return f"{type(self).__name__}{{{fields}}}"


class Task(Message):
    """A runnable wrapped as a self-addressed message; size 0 so it doesn't
    count as network traffic (messages/Task.java)."""

    def __init__(self, r: Callable[[], None]):
        assert r is not None
        self.r = r

    def size(self) -> int:
        return 0

    def action(self, network, from_node, to_node) -> None:
        self.r()


class PeriodicTask(Task):
    """Re-sends itself every `period` ms while the continuation condition
    holds (messages/PeriodicTask.java:40-47)."""

    def __init__(self, r, from_node, period: int, condition=None):
        super().__init__(r)
        self.period = period
        self.sender = from_node
        self.continuation_condition = condition if condition is not None else (lambda: True)

    def action(self, network, from_node, to_node) -> None:
        self.r()
        if self.continuation_condition():
            network.send_arrive_at(self, network.time + self.period, self.sender, self.sender)


class ConditionalTask(Task):
    """Polled by the engine on empty milliseconds (Network.nextMessage);
    fields per messages/ConditionalTask.java."""

    def __init__(self, start_if, repeat_if, r, min_start_time: int, from_node, duration: int):
        super().__init__(r)
        self.start_if = start_if
        self.repeat_if = repeat_if
        self.duration = duration
        self.min_start_time = min_start_time
        self.from_node = from_node


class FloodMessage(Message):
    """Gossip primitive: dedup per (node, msgId), then re-broadcast to the
    node's peers in shuffled order with local/per-peer delays
    (messages/FloodMessage.java:47-56)."""

    def __init__(self, size: int = 0, local_delay: int = 0, delay_between_peers: int = 0):
        self._size = size
        self.local_delay = local_delay
        self.delay_between_peers = delay_between_peers

    def msg_id(self) -> int:
        return -1

    def add_to_received(self, to: "P2PNode") -> bool:
        s = to.get_msg_received(self.msg_id())
        if self in s:
            return False
        s.add(self)
        return True

    def action(self, network, from_node, to_node) -> None:
        if self.add_to_received(to_node):
            to_node.on_flood(from_node, self)
            dest = [n for n in to_node.peers if n is not from_node]
            network.rd.shuffle(dest)
            network.send(
                self,
                network.time + 1 + self.local_delay,
                to_node,
                dest,
                self.delay_between_peers,
            )

    def size(self) -> int:
        return self._size

    def __hash__(self):
        return id(self)

    def __eq__(self, other):
        return self is other


class StatusFloodMessage(FloodMessage):
    """Versioned flood: only the highest seq per msgId is kept/propagated
    (messages/StatusFloodMessage.java:31-44)."""

    def __init__(self, msg_id: int, seq: int, size: int, local_delay: int, delay_between_peers: int):
        super().__init__(size, local_delay, delay_between_peers)
        if msg_id < 0:
            raise ValueError(f"id less than zero are reserved, msgId={msg_id}")
        self._msg_id = msg_id
        self.seq = seq

    def msg_id(self) -> int:
        return self._msg_id

    def add_to_received(self, to: "P2PNode") -> bool:
        s = to.get_msg_received(self._msg_id)
        previous = next(iter(s)) if s else None
        if previous is not None and previous.seq >= self.seq:
            return False
        s.clear()
        s.add(self)
        return True


class SendMessage:
    """Wire DTO for message injection via the API / External hook
    (messages/SendMessage.java)."""

    def __init__(
        self,
        from_id: int,
        to: List[int],
        send_time: int,
        delay_between_send: int,
        message: Optional[Message],
    ):
        self.from_id = from_id
        self.to = to
        self.send_time = send_time
        self.delay_between_send = delay_between_send
        self.message = message
